"""Balanced shard allocation + deciders + rebalance (VERDICT r2 next #4).

Unit tier: deciders and the weight-driven allocator over synthetic routing
tables. Integration tier: a late-started 4th node receives shards via
staged relocation; awareness keeps copies across zones."""

import json
import os
import time

import pytest

from elasticsearch_tpu.cluster.allocation import (
    AllocationContext, AwarenessDecider, BalancedAllocator, DiskThresholdDecider,
    FilterDecider, MaxRetryDecider, SameShardDecider, decide, explain)

NODES = ["n0", "n1", "n2"]


def ctx_with(routing=None, meta=None, **kw):
    return AllocationContext(kw.pop("nodes", NODES), routing or {},
                             meta or {}, **kw)


# ---------------------------------------------------------------------------
# deciders
# ---------------------------------------------------------------------------


def test_same_shard_decider():
    ctx = ctx_with({"i": {"0": {"primary": "n0", "replicas": ["n1"]}}})
    d = SameShardDecider()
    assert d.can_allocate("i", 0, "n0", ctx).verdict == "NO"
    assert d.can_allocate("i", 0, "n1", ctx).verdict == "NO"
    assert d.can_allocate("i", 0, "n2", ctx).verdict == "YES"


def test_filter_decider_require_exclude():
    meta = {"i": {"settings": {
        "index.routing.allocation.require._name": "n1"}}}
    d = FilterDecider()
    ctx = ctx_with({}, meta)
    assert d.can_allocate("i", 0, "n0", ctx).verdict == "NO"
    assert d.can_allocate("i", 0, "n1", ctx).verdict == "YES"
    meta2 = {"i": {"settings": {
        "index.routing.allocation.exclude.zone": "z1"}}}
    ctx2 = ctx_with({}, meta2,
                    node_attrs={"n0": {"zone": "z1"}, "n1": {"zone": "z2"}})
    assert d.can_allocate("i", 0, "n0", ctx2).verdict == "NO"
    assert d.can_allocate("i", 0, "n1", ctx2).verdict == "YES"


def test_awareness_decider_spreads_zones():
    attrs = {"n0": {"zone": "a"}, "n1": {"zone": "a"}, "n2": {"zone": "b"}}
    d = AwarenessDecider()
    # primary already in zone a -> the replica must go to zone b
    ctx = ctx_with({"i": {"0": {"primary": "n0", "replicas": []}}},
                   node_attrs=attrs)
    assert d.can_allocate("i", 0, "n1", ctx).verdict == "NO"
    assert d.can_allocate("i", 0, "n2", ctx).verdict == "YES"


def test_disk_threshold_decider():
    d = DiskThresholdDecider()
    ctx = ctx_with({}, disk_used={"n0": 0.95, "n1": 0.30})
    assert d.can_allocate("i", 0, "n0", ctx).verdict == "NO"
    assert d.can_allocate("i", 0, "n1", ctx).verdict == "YES"
    assert d.can_allocate("i", 0, "n2", ctx).verdict == "YES"  # unknown


def test_max_retry_decider_and_explain():
    routing = {"i": {"0": {"primary": None, "replicas": [],
                           "failed_attempts": 5}}}
    ctx = ctx_with(routing)
    assert MaxRetryDecider().can_allocate("i", 0, "n0", ctx).verdict == "NO"
    doc = explain("i", 0, ctx)
    assert doc["can_allocate"] == "no"
    assert all(n["node_decision"] == "no"
               for n in doc["node_allocation_decisions"])
    reasons = [d["decider"] for n in doc["node_allocation_decisions"]
               for d in n["deciders"]]
    assert "max_retry" in reasons


# ---------------------------------------------------------------------------
# balanced allocator
# ---------------------------------------------------------------------------


def test_allocate_index_balances_across_nodes():
    ctx = ctx_with({})
    alloc = BalancedAllocator()
    alloc.allocate_index("a", 3, 0, ctx)
    alloc.allocate_index("b", 3, 0, ctx)
    per_node = {}
    for table in ctx.routing.values():
        for e in table.values():
            per_node[e["primary"]] = per_node.get(e["primary"], 0) + 1
    assert sorted(per_node.values()) == [2, 2, 2], per_node


def test_allocate_unassigned_fills_missing_replicas():
    routing = {"i": {"0": {"primary": "n0", "replicas": []}}}
    meta = {"i": {"num_replicas": 1}}
    ctx = ctx_with(routing, meta)
    placed = BalancedAllocator().allocate_unassigned(ctx)
    assert placed == 1
    assert routing["i"]["0"]["replicas"], routing


def test_plan_rebalance_moves_to_empty_node():
    # everything piled on n0 -> moves toward n1/n2 proposed
    routing = {"i": {str(s): {"primary": "n0", "replicas": []}
                     for s in range(4)}}
    ctx = ctx_with(routing, {"i": {"num_replicas": 0}})
    moves = BalancedAllocator().plan_rebalance(ctx)
    assert moves, "expected rebalance moves"
    assert all(m["from"] == "n0" and m["to"] in ("n1", "n2")
               for m in moves)


# ---------------------------------------------------------------------------
# integration: late-joining node receives shards; awareness spreads zones
# ---------------------------------------------------------------------------

BASE_PORT = 29940


@pytest.mark.slow
def test_late_node_join_triggers_rebalance(tmp_path):
    from elasticsearch_tpu.node.cluster_node import ClusterNode
    peers = {f"n{i}": ("127.0.0.1", BASE_PORT + i) for i in range(4)}
    nodes = [ClusterNode(f"n{i}", "127.0.0.1", BASE_PORT + i, peers,
                         str(tmp_path / f"n{i}"), seed=i)
             for i in range(3)]                    # n3 NOT started yet
    late = None
    try:
        deadline = time.monotonic() + 15
        leader = None
        while leader is None and time.monotonic() < deadline:
            ls = [n for n in nodes if n.coordinator.mode == "LEADER"]
            if len(ls) == 1:
                leader = ls[0]
            time.sleep(0.05)
        assert leader is not None
        client = nodes[0]
        st, _, out = client.rest.handle("PUT", "/r", "", json.dumps({
            "settings": {"number_of_shards": 6, "number_of_replicas": 0},
            "mappings": {"properties": {"v": {"type": "long"}}}}).encode())
        assert st == 200, out
        for i in range(30):
            client.rest.handle("PUT", f"/r/_doc/{i}", "",
                               json.dumps({"v": i}).encode())
        client.rest.handle("POST", "/r/_refresh", "", b"")

        # join the 4th node: the allocator must MOVE shards onto it and
        # the data must survive the relocation
        late = ClusterNode("n3", "127.0.0.1", BASE_PORT + 3, peers,
                           str(tmp_path / "n3"), seed=3)
        deadline = time.monotonic() + 40
        moved = False
        while time.monotonic() < deadline:
            stt = client.node_loop.sync(
                lambda: client.coordinator.applied)
            table = stt.data.get("routing", {}).get("r", {})
            owners = {e["primary"] for e in table.values()}
            if "n3" in owners and not any(
                    e.get("relocating_to") for e in table.values()):
                moved = True
                break
            time.sleep(0.3)
        assert moved, f"no shard moved to n3: {table}"
        st, _, out = client.rest.handle(
            "POST", "/r/_search", "",
            json.dumps({"size": 0, "track_total_hits": True}).encode())
        assert json.loads(out)["hits"]["total"]["value"] == 30
    finally:
        for n in nodes + ([late] if late else []):
            try:
                n.stop()
            except Exception:
                pass


@pytest.mark.slow
def test_awareness_keeps_copies_in_distinct_zones(tmp_path):
    from elasticsearch_tpu.node.cluster_node import ClusterNode
    base = BASE_PORT + 10
    attrs = {"n0": {"zone": "a"}, "n1": {"zone": "a"},
             "n2": {"zone": "b"}, "n3": {"zone": "b"}}
    peers = {f"n{i}": ("127.0.0.1", base + i) for i in range(4)}
    nodes = [ClusterNode(f"n{i}", "127.0.0.1", base + i, peers,
                         str(tmp_path / f"n{i}"), seed=i, node_attrs=attrs)
             for i in range(4)]
    try:
        deadline = time.monotonic() + 15
        leader = None
        while leader is None and time.monotonic() < deadline:
            ls = [n for n in nodes if n.coordinator.mode == "LEADER"]
            if len(ls) == 1:
                leader = ls[0]
            time.sleep(0.05)
        assert leader is not None
        client = nodes[0]
        st, _, out = client.rest.handle("PUT", "/az", "", json.dumps({
            "settings": {"number_of_shards": 4,
                         "number_of_replicas": 1}}).encode())
        assert st == 200, out
        stt = client.node_loop.sync(lambda: client.coordinator.applied)
        table = stt.data.get("routing", {}).get("az", {})
        zone = lambda n: attrs[n]["zone"]   # noqa: E731
        for sid, entry in table.items():
            copies = [entry["primary"]] + entry["replicas"]
            assert len(copies) == 2, (sid, entry)
            assert zone(copies[0]) != zone(copies[1]), (sid, entry)
    finally:
        for n in nodes:
            try:
                n.stop()
            except Exception:
                pass
