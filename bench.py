"""Headline benchmark: batched BM25 top-k QPS, TPU vs CPU reference.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload (BASELINE.md eval config #1 shape, synthetic stand-in for MS MARCO
since the image has no dataset): Zipf-distributed corpus, batched bag-of-words
queries, k=10. ``vs_baseline`` is TPU QPS / CPU QPS where the CPU reference is
a vectorized numpy CSR BM25 (per-term gather + scatter-add + argpartition
top-k — the same eager-scoring algorithm, honestly tuned for CPU; it stands in
for Lucene's BulkScorer loop which is not available in this image).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

N_DOCS = 1 << 18           # 262k docs
VOCAB = 1 << 16
AVG_DL = 32
BATCH = 64                 # queries per dispatch
N_TERMS = 4                # terms per query
K = 10
DF_MIN, DF_MAX = 16, 4096  # query terms drawn from mid-frequency vocab
TIMED_ITERS = 8
K1, B = 1.2, 0.75


def build_corpus(rng):
    from elasticsearch_tpu.utils.synth import synthetic_csr_corpus
    return synthetic_csr_corpus(rng, N_DOCS, VOCAB, AVG_DL, zipf_s=1.2)


def sample_queries(rng, corpus, n_batches):
    eligible = np.flatnonzero((corpus["df"] >= DF_MIN) & (corpus["df"] <= DF_MAX))
    batches = []
    for _ in range(n_batches):
        qs = [[f"t{t}" for t in rng.choice(eligible, N_TERMS, replace=False)]
              for _ in range(BATCH)]
        batches.append(qs)
    return batches


def cpu_bm25_search(corpus, batches, k):
    """Vectorized numpy CSR BM25 + argpartition top-k (CPU reference)."""
    offsets, docs, tf = corpus["offsets"], corpus["docs"], corpus["tf"]
    dl = corpus["doc_len"]
    avgdl = dl.mean()
    df = corpus["df"]
    out = []
    t0 = time.perf_counter()
    for qs in batches:
        for terms in qs:
            scores = np.zeros(N_DOCS, np.float32)
            for t in terms:
                tid = int(t[1:])
                st, en = offsets[tid], offsets[tid + 1]
                if en == st:
                    continue
                run_docs = docs[st:en]
                run_tf = tf[st:en]
                idf = np.log(1 + (N_DOCS - df[tid] + 0.5) / (df[tid] + 0.5))
                norm = run_tf + K1 * (1 - B + B * dl[run_docs] / avgdl)
                scores[run_docs] += idf * (K1 + 1) * run_tf / norm
            top = np.argpartition(-scores, k)[:k]
            out.append(top[np.argsort(-scores[top], kind="stable")])
    return time.perf_counter() - t0, out


def _init_jax_backend(retries: int = 3, backoff_s: float = 10.0):
    """Initialize the accelerator backend, retrying transient failures.

    Round-1 bench died inside ``jax.devices()`` with a transient "TPU backend
    setup/compile error" and produced no number at all. Retry with backoff;
    if the accelerator never comes up, fall back to CPU so the bench still
    emits a (clearly labeled) measurement instead of exiting nonzero.
    """
    import jax
    if os.environ.get("BENCH_FORCE_CPU"):
        # local/dev runs: the ambient sitecustomize registers the accelerator
        # backend and env vars alone can't override it — go through jax.config
        jax.config.update("jax_platforms", "cpu")
    last = None
    for attempt in range(retries):
        try:
            devs = jax.devices()
            print(f"# jax backend: {devs[0].platform} x{len(devs)}",
                  file=sys.stderr)
            return jax
        except Exception as e:  # backend init is the only thing that throws
            last = e
            print(f"# backend init attempt {attempt + 1}/{retries} failed: "
                  f"{e}", file=sys.stderr)
            if attempt + 1 < retries:
                time.sleep(backoff_s)
    print(f"# falling back to CPU after {retries} failures: {last}",
          file=sys.stderr)
    try:
        jax.config.update("jax_platforms", "cpu")
        jax.devices()
        return jax
    except Exception as e:
        raise SystemExit(f"no usable jax backend: {e}") from e


def main():
    rng = np.random.RandomState(1234)
    corpus = build_corpus(rng)
    corpus["term_ids"] = {f"t{t}": t for t in range(VOCAB)}

    # ---- CPU reference ----------------------------------------------------
    cpu_batches = sample_queries(rng, corpus, 2)
    cpu_s, _ = cpu_bm25_search(corpus, cpu_batches, K)
    cpu_qps = (2 * BATCH) / cpu_s

    # ---- TPU --------------------------------------------------------------
    jax = _init_jax_backend()
    from elasticsearch_tpu.parallel import DistributedSearchPlane, make_search_mesh

    n_dev = len(jax.devices())
    mesh = make_search_mesh(n_shards=n_dev, n_replicas=1)
    if n_dev > 1:
        # split corpus into per-device shards by doc id range
        raise SystemExit("multi-device bench path not wired yet")
    plane = DistributedSearchPlane(mesh, [corpus], field="body")

    warm = sample_queries(rng, corpus, 1)[0]
    plane.search(warm, k=K, Q=N_TERMS, L=DF_MAX)          # compile
    timed_batches = sample_queries(rng, corpus, TIMED_ITERS)
    t0 = time.perf_counter()
    for qs in timed_batches:
        vals, hits = plane.search(qs, k=K, Q=N_TERMS, L=DF_MAX)
    tpu_s = time.perf_counter() - t0
    tpu_qps = (TIMED_ITERS * BATCH) / tpu_s

    print(json.dumps({
        "metric": "bm25_topk_qps_262k_docs",
        "value": round(tpu_qps, 1),
        "unit": "queries/s",
        "vs_baseline": round(tpu_qps / cpu_qps, 2),
        # a CPU-fallback run must be distinguishable from a real TPU result
        "backend": jax.devices()[0].platform,
    }))


if __name__ == "__main__":
    main()
