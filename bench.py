"""All five BASELINE.md eval configs + the REST serving path, TPU vs CPU.

Prints ONE final JSON line (the headline config #1 metric) whose ``configs``
field embeds every other measurement; each config also logs its own JSON to
stderr as it completes.

Configs (synthetic stand-ins at the BASELINE.md shapes — the image has no
datasets):
1. ``match`` BM25 top-k, 2^23 Zipf docs, term-frequency-weighted queries
   with NO df cap (MS MARCO shape) — the tiered kernel
   (``ops/tiered_bm25.py``: dense-tier streaming matmul + sparse
   sorted-merge).
2. ``bool`` should-disjunction BM25 — same plane, 8-term queries (enwiki
   multi-term disjunction shape).
3. ``terms`` + ``percentiles`` aggregation — the exact cumsum+searchsorted
   percentile kernel (``ops/aggs.py:masked_ordinal_percentiles``) vs a
   numpy groupby (NYC-taxi shape: Zipf keyword + value column, filtered
   mask).
4. brute-force kNN — ``dist_search.build_knn_step`` blocked streaming
   einsum (pack-time corpus invariants + running top-k) at the
   GloVe-1.2M/d=100/k=100 shape vs numpy matmul+argpartition at the SAME
   batch size; both sides report achieved corpus GB/s.
5. hybrid BM25 + kNN RRF — plane top-100 + kNN top-100 + reciprocal-rank
   fusion, vs the same pipeline in numpy.
6. ``knn_ivf_recall`` — IVF cluster-pruned ANN (k-means coarse quantizer
   + int8 tier + exact re-rank) at 2^20 vectors: q/s AND recall@10 vs
   the exact blocked scan on the same plane (recall is measured overlap,
   never assumed).
Plus: the REST **serving** path under 32 concurrent clients through
``RestAPI.handle`` → plane route → micro-batching queue
(``search/microbatch.py``), reporting serving p50/p99 + observed batch
sizes — serving QPS and kernel QPS are different quantities and are
reported separately. A B∈{1,4,16,64} dispatch-latency curve validates
ROOFLINE.md's batching model. And **live_indexing_search**: search
throughput under interleaved bulk-index + refresh traffic, delta-tier
generations vs the legacy rebuild-every-refresh behavior (zero
synchronous request-thread repacks is the acceptance invariant).

``vs_baseline`` is device QPS / CPU-reference QPS; every CPU reference is
the same algorithm honestly tuned for numpy (standing in for Lucene's
BulkScorer loop, ``search/internal/ContextIndexSearcher.java:210-224``,
and the vectors script_score loop,
``x-pack/plugin/vectors/.../query/ScoreScriptUtils.java:112-136``).

p99 is per-query latency in the batched serving model: every query's latency
is its dispatch's wall time (host assembly + device step + result sync).

On >1 device the corpus splits into per-device doc-range shards and the
query batch runs SPMD over the (replica, shard) mesh; on the single tunneled
TPU chip it runs one-shard. BENCH_FORCE_CPU=1 runs a scaled-down CPU-mesh
variant (clearly labeled via "backend").
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# SLO-watchdog threshold at bench scale, set BEFORE the package imports:
# the bench intentionally measures degraded baselines (the eager 10M-doc
# lexical scan, the rebuild-every-refresh legacy leg) whose multi-second
# latencies ARE the comparison, not an incident; 8 s is the stall level
# that would mean a real hang. Under it, a steady-state run must record
# ZERO automatic captures — the false-positive invariant gated by
# scripts/bench_diff.py via ``watchdog_steady_captures`` below.
os.environ.setdefault("ES_TPU_SLO_LATENCY_MS", "8000")

VOCAB = 1 << 16
AVG_DL = 32
BATCH = 64                 # queries per dispatch
N_TERMS = 4                # terms per query
K = 10
TIMED_ITERS = 64           # percentile sample size: p99 interpolates near
                           # the top sample; 64 keeps the accel pass
                           # inside the driver's wall-clock budget over
                           # the tunneled chip
CPU_REF_QUERIES = 12       # CPU reference is ~4-8 s/query at 8.4M docs
K1, B = 1.2, 0.75


# ---------------------------------------------------------------------------
# Backend orchestration (parent process — NEVER touches a jax backend itself)
#
# Rounds 1 and 2 produced no perf number because jax backend init against the
# tunneled accelerator sometimes HANGS instead of throwing: an in-process
# retry loop around jax.devices() (the round-2 fix) blocks forever on attempt
# 2 and the driver's outer timeout kills the whole script (rc=124, no JSON).
# The only robust shape is process isolation: probe the backend in a
# subprocess with a hard wall-clock timeout, run the bench itself in a
# timeboxed subprocess, and fall back to forced-CPU (proven to work — the
# test suite runs on it) or, last resort, a pure-numpy measurement.
# A final JSON line is emitted UNCONDITIONALLY.
# ---------------------------------------------------------------------------

PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT", 120))
ACCEL_BENCH_TIMEOUT_S = int(os.environ.get("BENCH_ACCEL_TIMEOUT", 900))
CPU_BENCH_TIMEOUT_S = int(os.environ.get("BENCH_CPU_TIMEOUT", 600))

_PROBE_SRC = (
    "import jax; d = jax.devices(); print(d[0].platform, len(d), flush=True)"
)

#: on-disk probe verdict (BENCH_r05 paid 3×120 s of timed-out probes
#: EVERY run): the verdict is a per-machine fact, so it caches to a file
#: next to the bench. A success verdict is trusted until the file is
#: deleted; a failure verdict expires after BENCH_PROBE_CACHE_TTL
#: seconds (default 24 h — tunnels come and go) and
#: BENCH_PROBE_REFRESH=1 forces a fresh probe either way.
PROBE_CACHE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), ".bench_probe_cache.json")
PROBE_CACHE_FAIL_TTL_S = int(os.environ.get("BENCH_PROBE_CACHE_TTL",
                                            24 * 3600))


def _probe_cache_read() -> str | None:
    """Cached platform string, "" for a cached (unexpired) failure, or
    None when there is no usable cache entry."""
    if os.environ.get("BENCH_PROBE_REFRESH"):
        return None
    try:
        with open(PROBE_CACHE_PATH) as f:
            doc = json.load(f)
        plat = doc.get("platform", None)
        if plat:
            return str(plat)
        if plat == "" and time.time() - float(doc.get("ts", 0)) \
                < PROBE_CACHE_FAIL_TTL_S:
            return ""
    except (OSError, ValueError, TypeError):
        pass
    return None


def _probe_cache_write(platform: str) -> None:
    try:
        with open(PROBE_CACHE_PATH, "w") as f:
            json.dump({"platform": platform, "ts": time.time()}, f)
    except OSError:
        pass


PROBE_LOG: list = []          # every attempt's outcome, emitted in the JSON


def _probe_backend(attempts: int = 3, stagger_s: int = 15) -> str | None:
    """Ask a throwaway subprocess what jax backend comes up, with a hard
    timeout per attempt and a stagger between attempts (the tunnel hang is
    intermittent across rounds: r01 threw, r02/r03 hung — an init that
    fails now may succeed seconds later). Returns the platform string or
    None; every attempt's outcome lands in PROBE_LOG for the final JSON.
    The verdict caches to PROBE_CACHE_PATH so the worst case (3 timed-out
    probes = 6+ minutes) is paid once per machine, not once per run."""
    cached = _probe_cache_read()
    if cached is not None:
        PROBE_LOG.append(f"cached:{cached or 'none'}")
        print(f"# backend probe: cached verdict "
              f"[{cached or 'no backend'}] from {PROBE_CACHE_PATH}",
              file=sys.stderr)
        return cached or None
    for i in range(attempts):
        if i:
            time.sleep(stagger_s)
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True, text=True, timeout=PROBE_TIMEOUT_S,
            )
            if r.returncode == 0 and r.stdout.strip():
                plat, ndev = r.stdout.split()[:2]
                print(f"# backend probe: {plat} x{ndev}", file=sys.stderr)
                PROBE_LOG.append(f"ok:{plat}x{ndev}")
                _probe_cache_write(plat)
                return plat
            PROBE_LOG.append(f"rc={r.returncode}")
            print(f"# backend probe attempt {i + 1}/{attempts} rc="
                  f"{r.returncode}: {r.stderr.strip()[-300:]}",
                  file=sys.stderr)
        except subprocess.TimeoutExpired:
            PROBE_LOG.append(f"timeout{PROBE_TIMEOUT_S}s")
            print(f"# backend probe attempt {i + 1}/{attempts} timed out "
                  f"after {PROBE_TIMEOUT_S}s (hung init)", file=sys.stderr)
    _probe_cache_write("")          # failure verdict, TTL-bounded
    return None


def _run_child(mode: str, timeout_s: int) -> str | None:
    """Run `bench.py --child <mode>` under a hard timeout; return its final
    JSON stdout line, or None on timeout/failure."""
    print(f"# launching bench child mode={mode} timeout={timeout_s}s",
          file=sys.stderr)
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", mode],
            stdout=subprocess.PIPE, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        print(f"# bench child ({mode}) timed out after {timeout_s}s",
              file=sys.stderr)
        return None
    line = None
    for ln in (r.stdout or "").splitlines():
        ln = ln.strip()
        if ln.startswith("{") and ln.endswith("}"):
            line = ln
    if r.returncode != 0:
        print(f"# bench child ({mode}) rc={r.returncode}", file=sys.stderr)
        return None
    if line is None:
        print(f"# bench child ({mode}) emitted no JSON line", file=sys.stderr)
    return line


def _numpy_last_resort() -> None:
    """No usable jax backend at all: measure the numpy CSR reference alone so
    the driver still records a real (clearly labeled) number."""
    rng = np.random.RandomState(1234)
    from elasticsearch_tpu.utils.synth import synthetic_csr_corpus_fast
    n_docs = 1 << 16
    corpus = synthetic_csr_corpus_fast(rng, n_docs, VOCAB, AVG_DL, zipf_s=1.2)
    queries = sample_queries(rng, corpus, 1, batch=CPU_REF_QUERIES)[0]
    times, _ = cpu_bm25_search(corpus, queries, K)
    qps = len(times) / sum(times)
    print(json.dumps({
        "metric": f"bm25_topk_qps_{n_docs}_docs_uncapped_df",
        "value": round(qps, 1),
        "unit": "queries/s",
        "vs_baseline": 1.0,
        "p99_ms": round(float(np.percentile(times, 99) * 1e3), 2),
        "cpu_ref_qps": round(qps, 1),
        "n_devices": 0,
        "backend": "numpy-fallback-no-jax",
        "probe_attempts": PROBE_LOG,
    }))


def orchestrate() -> None:
    plan: list[tuple[str, int]] = []
    if not os.environ.get("BENCH_FORCE_CPU"):
        plat = _probe_backend()
        if plat is not None and plat != "cpu":
            plan.append(("accel", ACCEL_BENCH_TIMEOUT_S))
    plan.append(("cpu", CPU_BENCH_TIMEOUT_S))
    for mode, tmo in plan:
        line = _run_child(mode, tmo)
        if line is not None:
            try:
                doc = json.loads(line)
                doc["probe_attempts"] = PROBE_LOG
                line = json.dumps(doc)
            except ValueError:
                pass
            print(line, flush=True)
            return
    _numpy_last_resort()


def sample_queries(rng, corpus, n_batches, batch=BATCH):
    """Term-frequency-weighted query sampling, NO df cap: term t is drawn
    with probability ∝ its posting mass, like sampling words from real query
    logs — head terms (df ≈ N) appear constantly."""
    df = corpus["df"].astype(np.float64)
    eligible = np.flatnonzero(df >= 2)
    p = df[eligible] / df[eligible].sum()
    batches = []
    for _ in range(n_batches):
        draws = rng.choice(eligible, size=(batch, N_TERMS), p=p)
        batches.append([[f"t{t}" for t in row] for row in draws])
    return batches


def cpu_bm25_search(corpus, queries, k):
    """Vectorized numpy CSR BM25 + argpartition top-k (CPU reference).
    Returns (per-query seconds list, hits)."""
    offsets, docs, tf = corpus["offsets"], corpus["docs"], corpus["tf"]
    dl = corpus["doc_len"]
    n_docs = dl.shape[0]
    avgdl = dl.mean()
    df = corpus["df"]
    out, times = [], []
    for terms in queries:
        t0 = time.perf_counter()
        scores = np.zeros(n_docs, np.float32)
        for t in set(terms):
            tid = int(t[1:])
            st, en = offsets[tid], offsets[tid + 1]
            if en == st:
                continue
            run_docs = docs[st:en]
            run_tf = tf[st:en]
            idf = np.log(1 + (n_docs - df[tid] + 0.5) / (df[tid] + 0.5))
            w = terms.count(t)
            norm = run_tf + K1 * (1 - B + B * dl[run_docs] / avgdl)
            scores[run_docs] += w * idf * (K1 + 1) * run_tf / norm
        top = np.argpartition(-scores, k)[:k]
        out.append(top[np.argsort(-scores[top], kind="stable")])
        times.append(time.perf_counter() - t0)
    return times, out


def _score_one(corpus, terms, doc: int) -> float:
    """Exact CPU BM25 of one (query, doc) pair — the cross-check oracle."""
    offsets, docs, tf = corpus["offsets"], corpus["docs"], corpus["tf"]
    dl = corpus["doc_len"]
    n_docs = dl.shape[0]
    avgdl = dl.mean()
    s = 0.0
    for t in set(terms):
        tid = int(t[1:])
        st, en = offsets[tid], offsets[tid + 1]
        run = docs[st:en]
        i = np.searchsorted(run, doc)
        if i >= run.shape[0] or run[i] != doc:
            continue
        f = float(tf[st + i])
        idf = float(np.log(1 + (n_docs - corpus["df"][tid] + 0.5)
                           / (corpus["df"][tid] + 0.5)))
        s += terms.count(t) * idf * (K1 + 1) * f / (
            f + K1 * (1 - B + B * float(dl[doc]) / avgdl))
    return s


def _emit(name: str, doc: dict) -> dict:
    """Log one config's result line to stderr; return it for embedding."""
    print(json.dumps({"config": name, **doc}), file=sys.stderr)
    return doc


def _watchdog_steady_captures() -> int:
    """Automatic (slo_red) watchdog captures recorded in THIS process —
    the steady-state false-positive gate's evidence. Manual/seeded
    captures do not count."""
    try:
        from elasticsearch_tpu.common.telemetry import DEFAULT
        doc = DEFAULT.metrics_doc().get("es_watchdog_captures_total")
        if not doc:
            return 0
        return int(sum(s["value"] for s in doc["series"]
                       if s["labels"].get("trigger") == "slo_red"))
    except Exception:   # noqa: BLE001 — evidence only
        return 0


def _efficiency_snapshot() -> dict:
    """{kernel: (count, efficiency_sum)} from the roofline auditor's
    ``es_dispatch_efficiency_pct`` families — monotone, so per-config
    deltas are exact."""
    try:
        from elasticsearch_tpu.common.telemetry import DEFAULT
        doc = DEFAULT.metrics_doc().get("es_dispatch_efficiency_pct")
        out = {}
        for s in (doc or {}).get("series", ()):
            v = s["value"]
            if isinstance(v, dict):
                out[s["labels"].get("kernel", "?")] = (
                    int(v.get("count", 0)), float(v.get("sum", 0.0)))
        return out
    except Exception:   # noqa: BLE001 — evidence only
        return {}


def _efficiency_delta(before: dict) -> dict:
    """Per-kernel {n, mean_pct} audited since ``before`` — the
    measured-vs-model summary each config embeds (scripts/bench_diff.py
    gates a >20% drop per kernel on paired configs)."""
    out = {}
    for k, (c1, s1) in _efficiency_snapshot().items():
        c0, s0 = before.get(k, (0, 0.0))
        if c1 > c0:
            out[k] = {"n": c1 - c0,
                      "mean_pct": round((s1 - s0) / (c1 - c0), 3)}
    return out


def _telemetry_snapshot() -> dict:
    """Final telemetry registry rollup for the bench JSON: compile
    counts/ms per site, device bytes moved, live-memory watermark — a
    compile-churn regression is then visible in the BENCH_r* trajectory,
    not just as an unexplained p99."""
    try:
        from elasticsearch_tpu.common.telemetry import device_stats_doc
        doc = device_stats_doc()
        out = {
            "compiles": doc.get("compiles", {}),
            "compile_millis": doc.get("compile_millis", {}),
            "transfer_bytes": doc.get("transfer", {}),
            "live_array_bytes_watermark":
                doc.get("live_array_bytes_watermark", 0),
        }
        # per-task resource attribution rollup (es_task_* families):
        # the serving benches run through RestAPI.handle, so the
        # attribution overhead and its outputs land in the trajectory
        try:
            from elasticsearch_tpu.common.telemetry import DEFAULT
            snap = DEFAULT.stats_doc()
            tasks = {}
            for fam in ("es_task_cpu_millis_total",
                        "es_task_device_millis_total",
                        "es_task_docs_scanned_total"):
                f = snap.get(fam)
                if f:
                    tasks[fam] = round(sum(
                        s["value"] for s in f["series"]), 1)
            if tasks:
                out["task_attribution"] = tasks
        except Exception:   # noqa: BLE001 — optional section
            pass
        return out
    except Exception as e:   # noqa: BLE001 — telemetry must never cost
        return {"error": repr(e)[:200]}    # the headline number


def _rrf(rank_lists, k, rrf_k=60):
    """Reciprocal-rank fusion over per-retriever doc-id rank lists
    (reference: ``RRFRankDoc`` semantics — score Σ 1/(rrf_k + rank))."""
    scores: dict = {}
    for ranks in rank_lists:
        for r, doc in enumerate(ranks):
            scores[doc] = scores.get(doc, 0.0) + 1.0 / (rrf_k + r + 1)
    return sorted(scores, key=lambda d: (-scores[d], d))[:k]


def bench_bool_disjunction(rng, corpus, plane, on_cpu):
    """Config #2: bool should-disjunction = 8-term bag-of-terms queries
    through the same tiered kernel (weights via duplicate terms)."""
    n_terms = 8
    iters = 16 if on_cpu else 24
    df = corpus["df"].astype(np.float64)
    eligible = np.flatnonzero(df >= 2)
    p = df[eligible] / df[eligible].sum()
    batches = []
    for _ in range(iters + 1):
        draws = rng.choice(eligible, size=(BATCH, n_terms), p=p)
        batches.append([[f"t{t}" for t in row] for row in draws])
    cpu_qs = batches[0][:8]
    cpu_times, _ = cpu_bm25_search(corpus, cpu_qs, K)
    cpu_qps = len(cpu_times) / sum(cpu_times)
    Q = 8
    Lb = workload_L(plane, batches, Q)
    plane.search(batches[0], k=K, Q=Q, L=Lb, tiered=plane.T_pad > 0)
    lat = []
    for qs in batches[1:]:
        t0 = time.perf_counter()
        if on_cpu:
            plane.search_eager(qs, k=K)
        else:
            plane.search(qs, k=K, Q=Q, L=Lb,
                         tiered=plane.T_pad > 0)
        lat.append(time.perf_counter() - t0)
    lat = np.asarray(lat)
    qps = (len(lat) * BATCH) / lat.sum()
    return _emit("bool_disjunction", {
        "value": round(qps, 1), "unit": "queries/s",
        "vs_baseline": round(qps / cpu_qps, 2),
        "p99_ms": round(float(np.percentile(lat, 99) * 1e3), 2),
        "n_terms": n_terms, "cpu_ref_qps": round(cpu_qps, 1)})


def bench_batch_curve(rng, corpus, plane, on_cpu):
    """Dispatch-latency curve over batch size — validates ROOFLINE.md's
    claim that one dispatch amortizes over the batch dimension."""
    curve = {}
    for b in (1, 4, 16, 64):
        qs = sample_queries(rng, corpus, 1, batch=b)[0]
        Lc = workload_L(plane, [qs], N_TERMS)
        plane.search(qs, k=K, Q=N_TERMS, L=Lc,
                     tiered=plane.T_pad > 0)        # compile this B
        ts = []
        for _ in range(5):
            t0 = time.perf_counter()
            plane.search(qs, k=K, Q=N_TERMS, L=Lc,
                         tiered=plane.T_pad > 0)
            ts.append(time.perf_counter() - t0)
        curve[str(b)] = round(float(np.median(ts)) * 1e3, 2)
    return _emit("batch_latency_curve_ms", curve)


def bench_terms_percentiles(rng, on_cpu):
    """Config #3: terms(top 10 of 256 Zipf ordinals) + exact percentiles
    [50, 95, 99] under a filter mask — device cumsum+searchsorted kernel
    (``ops/aggs.py``) vs numpy groupby."""
    import jax.numpy as jnp
    from elasticsearch_tpu.ops import aggs as ops_aggs
    n = (1 << 18) if on_cpu else (1 << 23)
    V = 256
    ranks = np.arange(1, V + 1, dtype=np.float64)
    pmf = ranks ** -1.1
    pmf /= pmf.sum()
    ords = rng.choice(V, size=n, p=pmf).astype(np.int32)
    vals = rng.lognormal(3.0, 1.0, n).astype(np.float32)
    order = np.lexsort((vals, ords))
    ords_s, docs_s, vals_s = (ords[order],
                              np.arange(n, dtype=np.int32)[order],
                              vals[order])
    offsets = np.cumsum(np.concatenate(
        [[0], np.bincount(ords_s, minlength=V)])).astype(np.int32)
    d_off = jnp.asarray(offsets)
    d_docs = jnp.asarray(docs_s)
    d_vals = jnp.asarray(vals_s)
    qs = [50.0, 95.0, 99.0]
    iters = 8 if on_cpu else 32
    masks = [rng.rand(n) < 0.25 for _ in range(iters + 1)]

    def device_agg(mask_np):
        mask = jnp.asarray(mask_np)
        counts, _c = ops_aggs.masked_rank_prefix(d_off, d_docs, mask)
        _vals_top, top = ops_aggs.top_ordinals(counts, 10)
        return top, ops_aggs.masked_ordinal_percentiles(
            d_off, d_docs, d_vals, mask, top.astype(np.int32), qs)

    top0, dev0 = device_agg(masks[0])            # compile + cross-check
    m0 = masks[0]
    cpu_t0 = time.perf_counter()
    cnt0 = np.bincount(ords[m0], minlength=V)
    top_cpu = np.argsort(-cnt0, kind="stable")[:10]
    ref0 = np.stack([np.percentile(vals[m0 & (ords == o)], qs,
                                   method="hazen") for o in top_cpu])
    cpu_per_agg = time.perf_counter() - cpu_t0
    assert list(top0) == list(top_cpu), "terms top-10 mismatch"
    if not np.allclose(dev0, ref0, rtol=1e-3, atol=1e-3):
        raise SystemExit(f"percentile mismatch: {dev0} vs {ref0}")
    ts = []
    for m in masks[1:]:
        t0 = time.perf_counter()
        _t, out = device_agg(m)
        np.asarray(out)
        ts.append(time.perf_counter() - t0)
    ts = np.asarray(ts)
    aps = 1.0 / ts.mean()
    cpu_aps = 1.0 / cpu_per_agg
    return _emit("terms_percentiles_agg", {
        "value": round(aps, 2), "unit": "aggs/s",
        "vs_baseline": round(aps / cpu_aps, 2),
        "p99_ms": round(float(np.percentile(ts, 99) * 1e3), 2),
        "n_docs": n, "exactness": "exact-vs-tdigest-approx",
        "cpu_ref_aggs_per_s": round(cpu_aps, 2)})


def bench_knn(rng, mesh, on_cpu):
    """Config #4: brute-force kNN at the GloVe shape (1.2M × d=100,
    k=100) — the ``DistributedKnnPlane`` (pack-time corpus invariants +
    blocked streaming running-top-k) vs numpy matmul+argpartition. The
    CPU reference scores the SAME B=16 batches the plane scores (the old
    4-query slice made vs_baseline
    apples-to-oranges), and both sides report achieved corpus GB/s
    (vectors read once per batch)."""
    from elasticsearch_tpu.parallel.dist_search import DistributedKnnPlane
    n_vec = (1 << 17) if on_cpu else 1_200_000
    dim, k, B = 100, 100, 16
    n_dev = mesh.devices.size
    per = -(-n_vec // n_dev)
    shard_vecs = []
    for s in range(n_dev):
        take = min(per, max(0, n_vec - s * per))
        shard_vecs.append(rng.randn(take, dim).astype(np.float32))
    # the plane packs vectors WITH their corpus invariants once (cosine
    # rows unit-normalized at pack time — the old step re-normalized the
    # corpus on every dispatch) and serves the blocked running-top-k step;
    # on a CPU backend it serves search_host (the search_eager analogue:
    # same blocked streaming design, BLAS matmul + threshold-pruned block
    # selection) while the jitted kernel is timed separately
    plane = DistributedKnnPlane(mesh, [dict(vectors=v) for v in shard_vecs],
                                similarity="cosine")
    host_serving = plane._host_pack is not None
    qs = rng.randn(B, dim).astype(np.float32)
    vals, _hits = plane.serve(qs, k=k)           # compile/warm
    # numpy reference: same cosine + top-k, same B=16 batch size, corpus
    # normalized once outside the timed loop (its own pack-time invariant)
    flat = np.concatenate(shard_vecs)
    fn = flat / np.maximum(
        np.linalg.norm(flat, axis=1, keepdims=True), 1e-12)
    cpu_iters = 6 if on_cpu else 1
    cpu_batches = [rng.randn(B, dim).astype(np.float32)
                   for _ in range(cpu_iters)]
    t0 = time.perf_counter()
    for qb in [qs] + cpu_batches:
        qn = qb / np.maximum(
            np.linalg.norm(qb, axis=1, keepdims=True), 1e-12)
        sc = qn @ fn.T
        part = np.argpartition(-sc, k, axis=1)[:, :k]
        for row, p_row in zip(sc, part):
            p_row[np.argsort(-row[p_row], kind="stable")]
        if qb is qs:
            sc_first = sc
            t0 = time.perf_counter()      # cross-check batch not timed
    cpu_s = time.perf_counter() - t0
    cpu_qps = (cpu_iters * B) / cpu_s
    # cross-check: top-1 score of query 0 matches numpy
    ref_top = float(np.max(sc_first[0]))
    got_top = float(np.asarray(vals)[0][0])
    if abs(got_top - ref_top) > 0.01 * max(1.0, abs(ref_top)):
        raise SystemExit(f"knn mismatch: {got_top} vs {ref_top}")
    iters = 16 if on_cpu else 32
    ts = []
    for _ in range(iters):
        qb = rng.randn(B, dim).astype(np.float32)
        t0 = time.perf_counter()
        vals, _hits = plane.serve(qb, k=k)
        ts.append(time.perf_counter() - t0)
    ts = np.asarray(ts)
    qps = (iters * B) / ts.sum()
    kernel_cpu_qps = None
    if host_serving:
        plane.search(qs, k=k)                    # compile the jitted step
        t0 = time.perf_counter()
        for qb in cpu_batches:
            plane.search(qb, k=k)
        kernel_cpu_qps = (cpu_iters * B) / (time.perf_counter() - t0)
    # achieved bandwidth: the blocked path reads the corpus once per
    # batch (ROOFLINE.md kNN section) — n_vec·dim·4 bytes per dispatch
    batch_bytes = n_vec * dim * 4
    doc = {
        "value": round(qps, 1), "unit": "queries/s",
        "vs_baseline": round(qps / cpu_qps, 2),
        "p99_ms": round(float(np.percentile(ts, 99) * 1e3), 2),
        "n_vectors": int(n_vec), "dim": dim, "k": k,
        "gb_per_s": round(batch_bytes * iters / ts.sum() / 1e9, 2),
        "cpu_ref_qps": round(cpu_qps, 1),
        "cpu_ref_gb_per_s": round(batch_bytes * cpu_iters / cpu_s / 1e9,
                                  2)}
    if kernel_cpu_qps is not None:
        doc["serving_path"] = "host-blocked-topk"
        doc["kernel_cpu_qps"] = round(kernel_cpu_qps, 1)
    return _emit("knn_bruteforce_glove_shape", doc)


def bench_knn_ivf(rng, mesh, on_cpu):
    """Config: IVF cluster-pruned ANN at 2^20 (≥1M) vectors — q/s AND
    recall@10 vs the exact blocked scan on the SAME plane, same queries.

    The corpus is clustered synthetic embeddings (mixture of Gaussians;
    iid-gaussian has no neighborhood structure for ANY index — real
    embedding corpora are clustered) and queries are perturbed corpus
    rows (the GloVe eval shape: queries live near the data manifold).
    The exact window serves ``nprobe=0`` (the brute-force fallback
    path); the IVF window serves the tier's benched defaults
    (nprobe/rerank), which is exactly what production dispatches use —
    the plane_serving health indicator flags anything below them.
    Recall is measured, not assumed: overlap@10 of IVF hits vs exact
    hits per query, averaged. Bytes-per-query for both tiers land in
    the JSON so the ROOFLINE IVF model is checkable from the BENCH
    trajectory."""
    from elasticsearch_tpu.parallel.dist_search import (
        IVF_DEFAULT_RERANK, DistributedKnnPlane)
    n_vec = 1 << 20
    dim, k, B = 64, 10, 16
    nlist = 1024
    n_centers = 2048
    centers = rng.randn(n_centers, dim).astype(np.float32)
    corpus = np.empty((n_vec, dim), np.float32)
    chunk = 1 << 17
    for lo in range(0, n_vec, chunk):
        n = min(chunk, n_vec - lo)
        cidx = rng.randint(0, n_centers, n)
        corpus[lo: lo + n] = centers[cidx] \
            + 0.35 * rng.randn(n, dim).astype(np.float32)
    n_dev = mesh.devices.size
    per = -(-n_vec // n_dev)
    shards = [dict(vectors=corpus[s * per: (s + 1) * per])
              for s in range(n_dev)]
    # build timer starts HERE: index_build_s measures the pack (k-means
    # + assignment + quantize + reorder), not the synthetic-data loop
    t_build = time.perf_counter()
    plane = DistributedKnnPlane(
        mesh, shards, similarity="cosine",
        ivf=dict(nlist=nlist, seed=7))
    build_s = time.perf_counter() - t_build
    nprobe = plane.ivf.default_nprobe

    def q_batch(n):
        qidx = rng.randint(0, n_vec, n)
        return corpus[qidx] + 0.15 * rng.randn(n, dim).astype(np.float32)

    # shared eval batches: exact ground truth AND the recall numerator
    # come from the same queries
    n_eval = 4
    eval_b = [q_batch(B) for _ in range(n_eval)]
    plane.serve(eval_b[0], k=k, nprobe=0)        # warm exact path
    exact_hits, ts_exact = [], []
    for qb in eval_b:
        t0 = time.perf_counter()
        _v, hits = plane.serve(qb, k=k, nprobe=0)
        ts_exact.append(time.perf_counter() - t0)
        exact_hits.append(hits)
    exact_qps = (n_eval * B) / sum(ts_exact)
    ivf_hits = []
    iters = 12 if on_cpu else 24
    extra_b = [q_batch(B) for _ in range(iters - n_eval)]
    # warm pass over EVERY timed batch: the IVF step's compile shape
    # includes the probed-union width bucket, which is data-dependent —
    # serving each batch once caches every shape the window will hit,
    # so no XLA compile can land inside the timed loop
    for qb in eval_b + extra_b:
        plane.serve(qb, k=k)
    ts_ivf = []
    for qb in eval_b + extra_b:
        t0 = time.perf_counter()
        _v, hits = plane.serve(qb, k=k)
        ts_ivf.append(time.perf_counter() - t0)
        if len(ivf_hits) < n_eval:
            ivf_hits.append(hits)
    ts_ivf = np.asarray(ts_ivf)
    ivf_qps = (iters * B) / ts_ivf.sum()
    overlaps = []
    for eh, ih in zip(exact_hits, ivf_hits):
        for erow, irow in zip(eh, ih):
            overlaps.append(len(set(erow) & set(irow)) / max(len(erow), 1))
    recall = float(np.mean(overlaps))
    # bytes-per-query model terms (ROOFLINE IVF section): the pruned
    # scan reads ~nprobe/nlist of the int8 tier + the exact re-rank
    # gather; the exact scan streams the full f32 corpus
    q_bytes = int(n_vec * nprobe / plane.ivf.nlist * (dim + 8)
                  + IVF_DEFAULT_RERANK * k * dim * 4)
    return _emit("knn_ivf_recall", {
        "value": round(ivf_qps, 1), "unit": "queries/s",
        "vs_exact_scan": round(ivf_qps / exact_qps, 2),
        "recall_at_k": round(recall, 4), "k": k,
        "p99_ms": round(float(np.percentile(ts_ivf, 99) * 1e3), 2),
        "exact_qps": round(exact_qps, 1),
        "n_vectors": n_vec, "dim": dim,
        "nlist": plane.ivf.nlist, "nprobe": nprobe,
        "rerank": IVF_DEFAULT_RERANK,
        "quantized_bytes_per_query": q_bytes,
        "exact_scan_bytes_per_query": n_vec * dim * 4,
        "index_build_s": round(build_s, 1)})


def bench_lexical_prune(rng, mesh, on_cpu):
    """Config: block-max lexical pruning at 2-10M docs (default 2^22 =
    4.2M synthetic Zipf docs; BENCH_LEX_N_DOCS overrides) — q/s and
    blocks-skipped fraction for the rank-safe pruned scan vs the eager
    scan on the SAME plane, same queries, top-10.

    Rank-safety is ASSERTED in-bench: pruned results must be
    bit-identical to eager (values, hits, tie order) on the shared eval
    batches — a pruning bug fails the bench, it never reports a healthy
    speedup. The plane is built WITHOUT the dense matmul tier
    (``dense_threshold`` huge): this config measures the CPU host
    serving split (``search_eager`` vs ``search_pruned_eager``), where
    the dense tier is never read — at 4M docs it would be >2 GB of
    dead weight. CSR impact bytes before/after int8 quantization land
    in the JSON (the tier's resident-bytes win) and are asserted ≥2x.
    ``p99_gate: true`` opts this config into scripts/bench_diff.py's
    p99-latency gate."""
    from elasticsearch_tpu.parallel import DistributedSearchPlane
    from elasticsearch_tpu.utils.synth import synthetic_csr_corpus_fast
    n_docs = int(os.environ.get("BENCH_LEX_N_DOCS", 0)) or (1 << 22)
    vocab = 1 << 16
    B = 16
    corpus = synthetic_csr_corpus_fast(rng, n_docs, vocab, 16, zipf_s=1.2)
    corpus["term_ids"] = {f"t{t}": t for t in range(vocab)}
    t_build = time.perf_counter()
    plane = DistributedSearchPlane(mesh, [corpus], field="body",
                                   dense_threshold=1 << 30, blockmax={})
    build_s = time.perf_counter() - t_build
    tier = plane.blockmax
    imp_f32 = tier.impact_bytes_f32()
    imp_int8 = tier.impact_bytes_int8()
    if imp_f32 < 2 * imp_int8:
        raise SystemExit(
            f"int8 impact quantization under 2x: {imp_f32} -> {imp_int8}")
    df = corpus["df"].astype(np.float64)
    eligible = np.flatnonzero(df >= 2)
    p = df[eligible] / df[eligible].sum()

    def q_batch():
        draws = rng.choice(eligible, size=(B, N_TERMS), p=p)
        return [[f"t{t}" for t in row] for row in draws]

    # p99 over these dispatch samples feeds bench_diff's p99 gate —
    # keep enough of them that one noisy batch doesn't swing it
    n_eager = 3
    n_pruned = 16
    batches = [q_batch() for _ in range(n_pruned)]
    plane.serve(batches[0], k=K, prune=False)       # warm both paths
    plane.serve(batches[0], k=K, prune=True)
    eager_res, ts_eager = [], []
    for qb in batches[:n_eager]:
        t0 = time.perf_counter()
        res = plane.serve(qb, k=K, prune=False)
        ts_eager.append(time.perf_counter() - t0)
        eager_res.append(res)
    eager_qps = (n_eager * B) / sum(ts_eager)
    st: dict = {}
    ts_pruned = []
    pruned_res = []
    for qb in batches:
        stb: dict = {}
        t0 = time.perf_counter()
        res = plane.serve(qb, k=K, prune=True, stages=stb)
        ts_pruned.append(time.perf_counter() - t0)
        pruned_res.append(res)
        for key in ("lex_blocks_scored", "lex_blocks_total"):
            st[key] = st.get(key, 0) + stb.get(key, 0)
    ts_pruned = np.asarray(ts_pruned)
    pruned_qps = (n_pruned * B) / ts_pruned.sum()
    # rank-safety: pruned == eager EXACTLY on the shared batches
    for (ev, eh), (pv, ph) in zip(eager_res, pruned_res[:n_eager]):
        if not (np.array_equal(ev, pv) and eh == ph):
            raise SystemExit("lexical prune rank-safety violated: "
                             "pruned != eager")
    skipped = 1.0 - st["lex_blocks_scored"] / max(st["lex_blocks_total"],
                                                  1)
    return _emit("lexical_10m_prune", {
        "value": round(pruned_qps, 1), "unit": "queries/s",
        "vs_eager": round(pruned_qps / eager_qps, 2),
        "eager_qps": round(eager_qps, 1),
        "p99_ms": round(float(np.percentile(ts_pruned, 99) * 1e3), 2),
        "eager_p99_ms": round(
            float(np.percentile(ts_eager, 99) * 1e3), 2),
        "p99_gate": True,
        "blocks_skipped_frac": round(skipped, 4),
        "rank_safety": "asserted-bit-identical",
        "impact_bytes_f32": imp_f32,
        "impact_bytes_int8": imp_int8,
        "impact_bytes_ratio": round(imp_f32 / imp_int8, 2),
        "n_docs": n_docs, "k": K, "n_terms": N_TERMS,
        "index_build_s": round(build_s, 1)})


def bench_hybrid_rrf(rng, mesh, on_cpu):
    """Config #5: hybrid BM25 + kNN with reciprocal-rank fusion (window
    100, k=10) — both retrievers on device, fusion on host; vs the same
    two retrievers in numpy."""
    from elasticsearch_tpu.parallel import DistributedSearchPlane
    from elasticsearch_tpu.parallel.dist_search import DistributedKnnPlane
    from elasticsearch_tpu.utils.shapes import round_up_pow2
    from elasticsearch_tpu.utils.synth import (split_csr_shards,
                                               synthetic_csr_corpus_fast)
    n_hy = (1 << 16) if on_cpu else (1 << 20)
    dim, window, k_out = 100, 100, 10
    corpus = synthetic_csr_corpus_fast(rng, n_hy, 1 << 14, 16, zipf_s=1.2)
    corpus["term_ids"] = {f"t{t}": t for t in range(1 << 14)}
    n_dev = mesh.devices.size
    shards = split_csr_shards(corpus, n_dev) if n_dev > 1 else [corpus]
    for s in shards:
        s["term_ids"] = corpus["term_ids"]
    plane = DistributedSearchPlane(mesh, shards, field="body")
    n_pad = round_up_pow2(-(-n_hy // n_dev))
    shard_vecs = []
    for s in range(n_dev):
        take = min(n_pad, max(0, n_hy - s * n_pad))
        shard_vecs.append(rng.randn(take, dim).astype(np.float32))
    # vector retriever = the kNN plane (blocked step on device, host
    # blocked scorer on the CPU fallback — same split as the text plane)
    kplane = DistributedKnnPlane(
        mesh, [dict(vectors=v) for v in shard_vecs],
        similarity="dot_product")
    vecs_flat = np.concatenate(shard_vecs)
    B = 16

    # CPU serving parity with config #1: the text retriever serves eager
    # (term-at-a-time over precomputed impacts), the vector retriever the
    # host blocked scorer; on an accelerator both ride their kernels
    text_eager = on_cpu and plane._host_csr is not None

    def one_batch(qbags, qvecs, timed=True):
        t0 = time.perf_counter()
        if text_eager:
            _vals, hits = plane.search_eager(qbags, k=window)
        else:
            _vals, hits = plane.search(qbags, k=window, Q=N_TERMS,
                                       L=L_hy, tiered=plane.T_pad > 0)
        _kvals, khits = kplane.serve(qvecs, k=window)
        fused = []
        for bi in range(len(qbags)):
            text_ranks = [si * n_pad + d for (si, d) in hits[bi]]
            vec_ranks = [si * kplane.n_pad + d for (si, d) in khits[bi]]
            fused.append(_rrf([text_ranks, vec_ranks], k_out))
        return fused, time.perf_counter() - t0

    warm_b = sample_queries(rng, corpus, 1, batch=B)[0]
    warm_v = rng.randn(B, dim).astype(np.float32)
    iters = 8 if on_cpu else 24
    timed_b = [sample_queries(rng, corpus, 1, batch=B)[0]
               for _ in range(iters)]
    timed_v = [rng.randn(B, dim).astype(np.float32)
               for _ in range(iters)]
    L_hy = workload_L(plane, [warm_b] + timed_b)
    one_batch(warm_b, warm_v)
    # numpy reference on 4 queries: same retrievers, same fusion
    t0 = time.perf_counter()
    _times, cpu_hits = cpu_bm25_search(corpus, warm_b[:4], window)
    flat = vecs_flat
    sc = warm_v[:4] @ flat.T
    part = np.argpartition(-sc, window, axis=1)[:, :window]
    cpu_fused = []
    for bi in range(4):
        vr = part[bi][np.argsort(-sc[bi][part[bi]], kind="stable")]
        cpu_fused.append(_rrf([list(map(int, cpu_hits[bi])),
                               list(map(int, vr))], k_out))
    cpu_qps = 4 / (time.perf_counter() - t0)
    ts = []
    for qb, qv in zip(timed_b, timed_v):
        _f, dt = one_batch(qb, qv)
        ts.append(dt)
    ts = np.asarray(ts)
    qps = (iters * B) / ts.sum()
    return _emit("hybrid_bm25_knn_rrf", {
        "value": round(qps, 1), "unit": "queries/s",
        "vs_baseline": round(qps / cpu_qps, 2),
        "p99_ms": round(float(np.percentile(ts, 99) * 1e3), 2),
        "n_docs": n_hy, "window": window, "cpu_ref_qps": round(cpu_qps, 1)})


def bench_hybrid_rrf_fused(rng, on_cpu):
    """Config: hybrid RRF through the PRODUCT serving path — the
    one-dispatch fused planner (``search/query_planner.py``: lexical +
    kNN + rank fusion as ONE dispatch over the serving generations) vs
    the legacy two-dispatch flow (text query phase + knn plane dispatch
    + host-side RRF) on the SAME plane generations, same segments, same
    queries — apples-to-apples down to the micro-batcher.

    Correctness is asserted in-bench BEFORE any timing: fused results
    must be bit-identical to the legacy path (ids, scores, tie order,
    totals) on shared eval bodies — a fusion bug fails the bench, it
    never reports a healthy speedup. The fused:legacy throughput ratio
    is GATED at >= 1.5x (the PR 11 acceptance bar), and the fused timed
    window asserts ZERO steady-state XLA compiles (the (B, k, L,
    params) lattice absorbed every shape during warmup)."""
    from elasticsearch_tpu.common import telemetry as _tm
    from elasticsearch_tpu.index.mapping import MapperService
    from elasticsearch_tpu.index.segment import SegmentBuilder
    from elasticsearch_tpu.search.plane_route import ServingPlaneCache
    from elasticsearch_tpu.search.shard_search import ShardSearcher
    n_docs = int(os.environ.get("BENCH_FUSED_N_DOCS", 0)) or \
        ((1 << 15) if on_cpu else (1 << 17))
    dim, window, k_out = 64, 100, 10
    vocab_n = 4096
    mapper = MapperService({"properties": {
        "body": {"type": "text"},
        "vec": {"type": "dense_vector", "dims": dim,
                "similarity": "dot_product"}}})
    vocab = [f"w{i}" for i in range(vocab_n)]
    zipf = np.minimum(rng.zipf(1.3, size=(n_docs, 12)) - 1, vocab_n - 1)
    vecs = rng.randn(n_docs, dim).astype(np.float32)
    t_build = time.perf_counter()
    sb = SegmentBuilder("s0")
    for i in range(n_docs):
        sb.add(mapper.parse_document(
            str(i), {"body": " ".join(vocab[t] for t in zipf[i]),
                     "vec": vecs[i].tolist()}), seq_no=i)
    segs = [sb.build()]
    build_s = time.perf_counter() - t_build
    cache = ServingPlaneCache()

    def searcher(fused):
        return ShardSearcher(
            segs, mapper,
            plane_provider=lambda s, f: cache.plane_for(s, mapper, f),
            knn_plane_provider=lambda s, f:
                cache.knn_plane_for(s, mapper, f),
            fused_provider=(lambda s, tf, kf:
                            cache.fused_runner_for(s, mapper, tf, kf))
            if fused else None)

    def body_of(i):
        r2 = np.random.RandomState(1000 + i)
        terms = " ".join(vocab[min(r2.zipf(1.3) - 1, vocab_n - 1)]
                         for _ in range(N_TERMS))
        return {"query": {"match": {"body": terms}},
                "knn": {"field": "vec",
                        "query_vector": [float(x) for x in
                                         r2.randn(dim)],
                        "k": k_out, "num_candidates": window},
                "rank": {"rrf": {"rank_window_size": window}},
                "size": k_out}

    n_eval, n_timed = 6, 24
    bodies = [body_of(i) for i in range(n_timed)]
    s_fused, s_legacy = searcher(True), searcher(False)
    # warm both paths (plane builds + batch shapes land here)
    s_legacy.search(dict(bodies[0]))
    s_fused.search(dict(bodies[0]))
    # bit-identity gate on the shared eval bodies
    for i in range(n_eval):
        rf = s_fused.search(dict(bodies[i]))
        rl = s_legacy.search(dict(bodies[i]))
        same = ([h.doc_id for h in rf.hits] ==
                [h.doc_id for h in rl.hits]
                and [h.score for h in rf.hits] ==
                [h.score for h in rl.hits]
                and (rf.total, rf.total_relation) ==
                (rl.total, rl.total_relation))
        if not same:
            raise SystemExit(
                "hybrid_rrf_fused parity violated: fused != two-dispatch")
    ts_leg = []
    for bdy in bodies:
        t0 = time.perf_counter()
        s_legacy.search(dict(bdy))
        ts_leg.append(time.perf_counter() - t0)
    compiles_before = _tm.compile_count()
    ts_fus = []
    for bdy in bodies:
        t0 = time.perf_counter()
        s_fused.search(dict(bdy))
        ts_fus.append(time.perf_counter() - t0)
    steady_compiles = _tm.compile_count() - compiles_before
    if steady_compiles:
        raise SystemExit(
            f"hybrid_rrf_fused: {steady_compiles} steady-state compiles "
            f"in the fused window (warm lattice failed)")
    ts_fus = np.asarray(ts_fus)
    fused_qps = n_timed / ts_fus.sum()
    legacy_qps = n_timed / sum(ts_leg)
    ratio = fused_qps / legacy_qps
    if ratio < 1.5:
        raise SystemExit(
            f"hybrid_rrf_fused below the 1.5x acceptance bar: "
            f"{ratio:.2f}x ({legacy_qps:.1f} -> {fused_qps:.1f} q/s)")
    planner = _tm.DEFAULT.metrics_doc().get("es_planner_lowered_total")
    fused_served = int(sum(
        s["value"] for s in (planner or {}).get("series", [])
        if s["labels"].get("outcome") == "fused"))
    cache.release()
    return _emit("hybrid_rrf_fused", {
        "value": round(fused_qps, 1), "unit": "queries/s",
        "vs_two_dispatch": round(ratio, 2),
        "two_dispatch_qps": round(legacy_qps, 1),
        "p99_ms": round(float(np.percentile(ts_fus, 99) * 1e3), 2),
        "two_dispatch_p99_ms": round(
            float(np.percentile(ts_leg, 99) * 1e3), 2),
        "p99_gate": True,
        "parity": "asserted-bit-identical",
        "steady_compiles": steady_compiles,
        "planner_fused_requests": fused_served,
        "n_docs": n_docs, "window": window, "k": k_out,
        "index_build_s": round(build_s, 1)})


def bench_analytics_fused(rng, on_cpu):
    """Config: device-resident analytics through the fused planner —
    mixed query+agg traffic (plain match queries, query+agg-tree
    requests, and size:0 pure-analytics requests, the live-serving
    client mix) against the SAME searcher with the fused provider
    withheld, where agg-carrying bodies fall back to the per-segment
    two-pass path (retrieval, then per-segment query re-execution for
    agg masks).

    Correctness is asserted in-bench BEFORE any timing: on shared eval
    bodies the fused route's hits AND aggregation trees must equal the
    host two-pass path exactly (int counts bitwise, the
    lexical_10m_prune rank-safety pattern applied to analytics). The
    fused:unfused throughput ratio is GATED at >= 2x on the mixed
    traffic, and the fused timed window asserts ZERO steady-state XLA
    compiles."""
    from elasticsearch_tpu.common import telemetry as _tm
    from elasticsearch_tpu.index.mapping import MapperService
    from elasticsearch_tpu.index.segment import SegmentBuilder
    from elasticsearch_tpu.search.plane_route import ServingPlaneCache
    from elasticsearch_tpu.search.shard_search import ShardSearcher
    n_docs = int(os.environ.get("BENCH_AGG_N_DOCS", 0)) or \
        ((1 << 15) if on_cpu else (1 << 17))
    vocab_n, n_tags = 2048, 32
    mapper = MapperService({"properties": {
        "body": {"type": "text"},
        "tag": {"type": "keyword"},
        "price": {"type": "double"},
        "ts": {"type": "date"}}})
    vocab = [f"w{i}" for i in range(vocab_n)]
    zipf = np.minimum(rng.zipf(1.3, size=(n_docs, 10)) - 1, vocab_n - 1)
    prices = rng.randint(0, 10_000, n_docs)
    t_build = time.perf_counter()
    segs = []
    per_seg = n_docs // 2
    for si in range(2):
        sb = SegmentBuilder(f"s{si}")
        for i in range(si * per_seg, (si + 1) * per_seg):
            sb.add(mapper.parse_document(str(i), {
                "body": " ".join(vocab[t] for t in zipf[i]),
                "tag": f"k{i % n_tags}",
                "price": float(prices[i]),
                "ts": int(1_700_000_000_000 + i * 60_000)}), seq_no=i)
        segs.append(sb.build())
    build_s = time.perf_counter() - t_build
    cache = ServingPlaneCache()

    def searcher(fused):
        return ShardSearcher(
            segs, mapper,
            plane_provider=lambda s, f: cache.plane_for(s, mapper, f),
            fused_provider=(lambda s, tf, kf:
                            cache.fused_runner_for(s, mapper, tf, kf))
            if fused else None)

    aggs_tree = {
        "tags": {"terms": {"field": "tag", "size": n_tags},
                 "aggs": {"p": {"avg": {"field": "price"}}}},
        "per_hour": {"date_histogram": {"field": "ts",
                                        "fixed_interval": "1h"}},
        "price_stats": {"stats": {"field": "price"}},
        "n_prices": {"cardinality": {"field": "price",
                                     "precision_threshold": 100}},
    }

    def body_of(i):
        r2 = np.random.RandomState(3000 + i)
        terms = " ".join(vocab[min(r2.zipf(1.3) - 1, vocab_n - 1)]
                         for _ in range(4))
        body = {"query": {"match": {"body": terms}}, "size": 10}
        if i % 4 == 1:
            return body                      # plain search traffic
        body["aggs"] = aggs_tree
        if i % 4 == 3:
            body["size"] = 0                 # pure analytics
        return body

    n_eval, n_timed = 8, 24
    bodies = [body_of(i) for i in range(n_timed)]
    s_fused, s_unfused = searcher(True), searcher(False)
    for w in (0, 1, 3):                      # warm every traffic class
        s_unfused.search(dict(bodies[w]))
        s_fused.search(dict(bodies[w]))
    # exactness gate on the shared eval bodies: hits, totals AND the
    # full aggregation trees (int counts are bitwise; sums/avgs run the
    # same reduce code on both routes)
    for i in range(n_eval):
        rf = s_fused.search(dict(bodies[i]))
        ru = s_unfused.search(dict(bodies[i]))
        same = ([h.doc_id for h in rf.hits] ==
                [h.doc_id for h in ru.hits]
                and rf.aggregations == ru.aggregations
                and (rf.total, rf.total_relation) ==
                (ru.total, ru.total_relation))
        if not same:
            raise SystemExit(
                f"analytics_fused exactness violated on body {i}: "
                f"fused != host two-pass")
    ts_unf = []
    for bdy in bodies:
        t0 = time.perf_counter()
        s_unfused.search(dict(bdy))
        ts_unf.append(time.perf_counter() - t0)
    compiles_before = _tm.compile_count()
    ts_fus = []
    for bdy in bodies:
        t0 = time.perf_counter()
        s_fused.search(dict(bdy))
        ts_fus.append(time.perf_counter() - t0)
    steady_compiles = _tm.compile_count() - compiles_before
    if steady_compiles:
        raise SystemExit(
            f"analytics_fused: {steady_compiles} steady-state compiles "
            f"in the fused window (agg plan lattice failed to warm)")
    ts_fus = np.asarray(ts_fus)
    fused_qps = n_timed / ts_fus.sum()
    unfused_qps = n_timed / sum(ts_unf)
    ratio = fused_qps / unfused_qps
    if ratio < 2.0:
        raise SystemExit(
            f"analytics_fused below the 2x acceptance bar: "
            f"{ratio:.2f}x ({unfused_qps:.1f} -> {fused_qps:.1f} q/s)")
    doc = _tm.DEFAULT.metrics_doc()
    planner = doc.get("es_planner_lowered_total")
    fused_served = int(sum(
        s["value"] for s in (planner or {}).get("series", [])
        if s["labels"].get("outcome") == "fused"))
    if not fused_served:
        raise SystemExit("analytics_fused: the planner never served — "
                         "the bench measured legacy vs legacy")
    agg_hist = doc.get("es_agg_stages_per_dispatch", {}).get("series")
    agg_dispatches = int(agg_hist[0]["value"]["count"]) if agg_hist \
        else 0
    dev_pairs = doc.get("es_agg_device_pairs_total", {}).get("series")
    cache.release()
    return _emit("analytics_fused", {
        "value": round(fused_qps, 1), "unit": "queries/s",
        "vs_unfused": round(ratio, 2),
        "unfused_qps": round(unfused_qps, 1),
        "p99_ms": round(float(np.percentile(ts_fus, 99) * 1e3), 2),
        "unfused_p99_ms": round(
            float(np.percentile(ts_unf, 99) * 1e3), 2),
        "exactness": "asserted-host-equal",
        "steady_compiles": steady_compiles,
        "agg_dispatches": agg_dispatches,
        "device_pairs": int(dev_pairs[0]["value"]) if dev_pairs else 0,
        "n_docs": n_docs, "n_segments": len(segs),
        "index_build_s": round(build_s, 1)})


def bench_serving(rng):
    """REST serving under concurrency: 32 client threads through
    ``RestAPI.handle`` → dispatcher-thread micro-batching queue. The
    headline window bypasses the plane request cache
    (``request_cache=false``) so it measures the DISPATCH pipeline —
    apples-to-apples with r05, which had no plane cache — and a second
    cache-enabled window reports the cached path (qps + hit/miss)
    separately. Serving p99 is a different quantity from kernel QPS
    (per-request wall time incl. parse, routing, fetch); per-stage
    (queue/prep/dispatch/fetch) p50/p99 come from the batcher's
    per-request samples, plus warm vs cold first-request latency and the
    warmup shape-lattice cost, so future PRs ratchet on stage numbers
    instead of one aggregate p99."""
    import tempfile
    import threading
    from elasticsearch_tpu.node.indices_service import IndicesService
    from elasticsearch_tpu.rest.api import RestAPI
    api = RestAPI(IndicesService(tempfile.mkdtemp(prefix="bench_srv_")))
    vocab = [f"w{i}" for i in range(64)]
    n_docs, lines = 4096, []
    for i in range(n_docs):
        body = " ".join(vocab[(i * 7 + j * 3) % 64] for j in range(8))
        lines.append(json.dumps({"index": {"_id": str(i)}}))
        lines.append(json.dumps({"body": body}))
    api.handle("POST", "/srv/_bulk", "refresh=true",
               ("\n".join(lines) + "\n").encode())
    # cold first request: plane build + first dispatch land here (what a
    # node's very first query pays)
    t0 = time.perf_counter()
    api.handle("POST", "/srv/_search", "",
               json.dumps({"query": {"match": {"body": "w3"}}}).encode())
    cold_first_ms = (time.perf_counter() - t0) * 1e3
    n_clients, per_client = 32, 8

    # warm the micro-batch compile shapes (pow2 B buckets) with one
    # untimed concurrent round — production is warm after its first
    # queries; the timed window should measure serving, not first-compile
    def warm_client(tid):
        for j in range(2):
            api.handle("POST", "/srv/_search", "", json.dumps(
                {"query": {"match": {"body": vocab[(tid + j) % 64]}}}
            ).encode())
    warmers = [threading.Thread(target=warm_client, args=(t,))
               for t in range(n_clients)]
    for t in warmers:
        t.start()
    for t in warmers:
        t.join()
    # warm first request through the DISPATCH path (request_cache=false
    # so the cache can't answer it): cold vs warm is the compile tax
    t0 = time.perf_counter()
    api.handle("POST", "/srv/_search", "request_cache=false",
               json.dumps({"query": {"match": {"body": "w3"}}}).encode())
    warm_first_ms = (time.perf_counter() - t0) * 1e3

    svc = api.indices.get("srv")

    def _batchers():
        out = []
        for gen in getattr(svc.plane_cache, "_planes", {}).values():
            b = getattr(gen, "_microbatcher", None)
            if b is not None:
                out.append(b)
        return out

    # snapshot so stage percentiles cover the timed window only
    # (warm-round compiles would pollute the p99)
    skip_n = {id(b): len(b.stage_samples["queue"]) for b in _batchers()}
    lock = threading.Lock()

    def run_window(params: str, per: int):
        lat, errs = [], []

        def client(tid):
            try:
                for j in range(per):
                    q = {"query": {"match": {
                        "body": vocab[(tid * per + j) % 64]}}}
                    t0 = time.perf_counter()
                    st, _ct, payload = api.handle(
                        "POST", "/srv/_search", params,
                        json.dumps(q).encode())
                    dt = time.perf_counter() - t0
                    doc = json.loads(payload)
                    assert st == 200 and doc["hits"]["total"]["value"] > 0
                    with lock:
                        lat.append(dt)
            except Exception as e:                 # noqa: BLE001
                with lock:
                    errs.append(repr(e))

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if errs:
            raise SystemExit(f"serving bench errors: {errs[:3]}")
        a = np.asarray(lat)
        return {"value": round(len(a) / wall, 1), "unit": "requests/s",
                "p50_ms": round(float(np.percentile(a, 50) * 1e3), 2),
                "p99_ms": round(float(np.percentile(a, 99) * 1e3), 2),
                "n_requests": int(len(a))}

    # headline: every request rides the dispatch pipeline (cache
    # bypassed — r05's number had no plane cache to compare against)
    dispatch_win = run_window("request_cache=false", per_client)
    batch_stats, stage_pcts = {}, {}
    for b in _batchers():
        doc = b.stats_doc()
        doc["mean_batch"] = round(doc["queries"] / max(doc["dispatches"],
                                                       1), 2)
        batch_stats = doc
        stage_pcts = b.stage_percentiles(skip=skip_n.get(id(b), 0))
    # cached path: identical plane-eligible bodies served from the shard
    # request cache before the batcher
    cache0 = dict(svc.plane_cache_stats)
    cached_win = run_window("", per_client)
    cached_win["hit_count"] = \
        svc.plane_cache_stats["hit_count"] - cache0["hit_count"]
    cached_win["miss_count"] = \
        svc.plane_cache_stats["miss_count"] - cache0["miss_count"]
    # insights overhead: the same dispatch-path traffic with query
    # fingerprinting + heavy-hitter sketches on vs off, interleaved
    # ABBA (on/off/off/on) so linear run-order drift — consecutive
    # identical windows swing >10% on a shared CPU — cancels out of
    # the pair; ``scripts/bench_diff.py`` gates ``pct_off_vs_on`` at
    # <= 2% (insights must be ~free on the hot path)
    arms = {"on": [], "off": []}
    prev_env = os.environ.get("ES_TPU_INSIGHTS")
    try:
        for arm in ("on", "off", "off", "on",
                    "on", "off", "off", "on"):
            os.environ["ES_TPU_INSIGHTS"] = \
                "1" if arm == "on" else "0"
            arms[arm].append(
                run_window("request_cache=false", per_client))
    finally:
        if prev_env is None:
            os.environ.pop("ES_TPU_INSIGHTS", None)
        else:
            os.environ["ES_TPU_INSIGHTS"] = prev_env

    def _arm_qps(wins):
        # total requests / total wall, not a mean of rates
        return sum(w["n_requests"] for w in wins) / \
            sum(w["n_requests"] / w["value"] for w in wins)

    on_qps, off_qps = _arm_qps(arms["on"]), _arm_qps(arms["off"])
    insights = {
        "on_qps": round(on_qps, 1), "off_qps": round(off_qps, 1),
        "on_p99_ms": round(max(w["p99_ms"] for w in arms["on"]), 2),
        "off_p99_ms": round(max(w["p99_ms"] for w in arms["off"]), 2),
        "pct_off_vs_on": round(
            (off_qps - on_qps) / max(on_qps, 1e-9) * 100.0, 2)}
    # continuous-profiler overhead: same ABBA discipline over the
    # always-on flamegraph sampler (ES_TPU_CONTPROF) — ensure_profiler()
    # actually starts/stops the sampler thread per arm, so the off arm
    # measures a truly sampler-free process; ``scripts/bench_diff.py``
    # gates ``pct_off_vs_on`` at <= 2% like the insights gate
    from elasticsearch_tpu.common import contprof as _contprof
    cp_arms = {"on": [], "off": []}
    prev_cp = os.environ.get("ES_TPU_CONTPROF")
    try:
        for arm in ("on", "off", "off", "on",
                    "on", "off", "off", "on"):
            os.environ["ES_TPU_CONTPROF"] = \
                "1" if arm == "on" else "0"
            _contprof.ensure_profiler()
            cp_arms[arm].append(
                run_window("request_cache=false", per_client))
    finally:
        if prev_cp is None:
            os.environ.pop("ES_TPU_CONTPROF", None)
        else:
            os.environ["ES_TPU_CONTPROF"] = prev_cp
        _contprof.ensure_profiler()
    cp_on, cp_off = _arm_qps(cp_arms["on"]), _arm_qps(cp_arms["off"])
    contprof = {
        "on_qps": round(cp_on, 1), "off_qps": round(cp_off, 1),
        "on_p99_ms": round(max(w["p99_ms"] for w in cp_arms["on"]), 2),
        "off_p99_ms": round(max(w["p99_ms"] for w in cp_arms["off"]), 2),
        "pct_off_vs_on": round(
            (cp_off - cp_on) / max(cp_on, 1e-9) * 100.0, 2)}
    return _emit("rest_serving_32_clients", {
        **dispatch_win, "n_clients": n_clients,
        "cold_first_request_ms": round(cold_first_ms, 2),
        "warm_first_request_ms": round(warm_first_ms, 2),
        "stages": stage_pcts,
        "cached": cached_win,
        "insights": insights,
        "contprof": contprof,
        "microbatch": batch_stats,
        "telemetry": _telemetry_snapshot()})



def bench_live_indexing(rng):
    """Live-indexing serving (the ROADMAP's logs/metrics NRT scenario):
    16 client threads search through ``RestAPI.handle`` while an indexer
    thread continuously bulk-indexes + refreshes — every refresh changes
    the segment list. Two windows, same harness style as
    ``rest_serving_32_clients``:

    - ``delta`` (default): incremental generations — appends ride the
      delta tier, repacks happen in the background. The acceptance
      invariant is ``request_thread_repacks == 0`` while the delta stays
      under threshold (the cold build is excluded).
    - ``rebuild_every_refresh``: the pre-generation behavior
      (``delta_enabled=False``) — every refresh forces a synchronous
      full repack on the next search's request thread.

    ``vs_rebuild_every_refresh`` is the headline ratio."""
    import tempfile
    import threading
    from elasticsearch_tpu.node.indices_service import IndicesService
    from elasticsearch_tpu.rest.api import RestAPI
    n_clients, per_client, n_seed = 16, 40, 16384
    vocab = [f"w{i}" for i in range(64)]
    out = {}
    for mode in ("delta", "rebuild_every_refresh"):
        api = RestAPI(IndicesService(
            tempfile.mkdtemp(prefix=f"bench_live_{mode}_")))
        lines = []
        for i in range(n_seed):
            body = " ".join(vocab[(i * 7 + j * 3) % 64] for j in range(8))
            lines.append(json.dumps({"index": {"_id": str(i)}}))
            lines.append(json.dumps({"body": body}))
        api.handle("POST", "/live/_bulk", "refresh=true",
                   ("\n".join(lines) + "\n").encode())
        svc = api.indices.get("live")
        cache = svc.plane_cache
        cache.delta_enabled = (mode == "delta")
        # cold build outside the window (both modes pay it once)
        api.handle("POST", "/live/_search", "request_cache=false",
                   json.dumps({"query": {"match": {"body": "w3"}}}
                              ).encode())
        rb0 = cache.rebuild_stats()
        refreshes0 = sum(s.stats.get("refresh_total", 0)
                         for s in svc.shards)
        stop = threading.Event()
        next_id = [n_seed]

        id_lock = threading.Lock()

        def indexer():
            while not stop.is_set():
                blines = []
                with id_lock:
                    lo = next_id[0]
                    next_id[0] += 8
                for i in range(lo, lo + 8):
                    body = " ".join(vocab[(i * 5 + j) % 64]
                                    for j in range(8))
                    blines.append(json.dumps({"index": {"_id": str(i)}}))
                    blines.append(json.dumps({"body": body}))
                api.handle("POST", "/live/_bulk", "refresh=true",
                           ("\n".join(blines) + "\n").encode())

        indexers = [threading.Thread(target=indexer, daemon=True)
                    for _ in range(2)]
        for ix in indexers:
            ix.start()
        lat, errs = [], []
        lock = threading.Lock()

        def client(tid):
            try:
                for j in range(per_client):
                    q = {"query": {"match": {
                        "body": vocab[(tid * per_client + j) % 64]}}}
                    t0 = time.perf_counter()
                    st, _ct, payload = api.handle(
                        "POST", "/live/_search", "request_cache=false",
                        json.dumps(q).encode())
                    dt = time.perf_counter() - t0
                    doc = json.loads(payload)
                    assert st == 200 and \
                        doc["hits"]["total"]["value"] > 0
                    with lock:
                        lat.append(dt)
            except Exception as e:                 # noqa: BLE001
                with lock:
                    errs.append(repr(e))

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        stop.set()
        for ix in indexers:
            ix.join(timeout=30)
        cache.drain_repacks()
        if errs:
            raise SystemExit(f"live-indexing bench errors: {errs[:3]}")
        rb = cache.rebuild_stats()
        a = np.asarray(lat)
        out[mode] = {
            "qps": round(len(a) / wall, 1),
            "p50_ms": round(float(np.percentile(a, 50) * 1e3), 2),
            "p99_ms": round(float(np.percentile(a, 99) * 1e3), 2),
            "n_requests": int(len(a)),
            "refreshes_in_window": int(
                sum(s.stats.get("refresh_total", 0)
                    for s in svc.shards) - refreshes0),
            # synchronous full repacks paid ON a request thread in the
            # window (delta mode: must be 0 — cold build is excluded)
            "request_thread_repacks": rb["sync"] - rb0["sync"],
            "background_repacks": rb["background"] - rb0["background"],
            "delta_served_queries": rb["delta_serves"]
            - rb0["delta_serves"],
        }
    ratio = out["delta"]["qps"] / max(out["rebuild_every_refresh"]["qps"],
                                      1e-9)
    return _emit("live_indexing_search", {
        "value": out["delta"]["qps"], "unit": "requests/s",
        "vs_rebuild_every_refresh": round(ratio, 2),
        "n_clients": n_clients, **out})


def bench_tiered_capacity(rng):
    """Tiered plane storage over-subscription: a per-field plane corpus
    ~10x the configured HBM budget serves a Zipf-skewed query mix
    through hot (device) / warm (host-streamed) / cold (pack-file)
    tiers with demand promotion. Two windows, same planes:

    - ``device``: unlimited budget, every plane hot — the baseline the
      acceptance gate compares against.
    - ``tiered``: ``hbm_budget ~= total/10`` (+ a host budget that
      forces cold spills) — the hot-set (most-queried field) p99 must
      stay within 1.25x of the device-resident p99, with ZERO
      steady-state pack rebuilds (cold promotions ride the
      handoff-import path, never re-pack) and zero new compiles.

    ``scripts/bench_diff.py`` gates hot_p99_ratio, the steady-state
    rebuild/journal invariants, and promotion-count drift between
    rounds."""
    import tempfile
    from elasticsearch_tpu.common import flightrec
    from elasticsearch_tpu.common.telemetry import device_stats_doc
    from elasticsearch_tpu.node.indices_service import IndicesService
    from elasticsearch_tpu.rest.api import RestAPI

    api = RestAPI(IndicesService(tempfile.mkdtemp(prefix="bench_tier_")))
    n_fields, n_docs = 12, 1024
    fields = [f"f{i}" for i in range(n_fields)]
    vocab = [f"w{i}" for i in range(64)]
    lines = []
    for i in range(n_docs):
        doc = {f: " ".join(vocab[(i * 7 + j * 3 + fi) % 64]
                           for j in range(6))
               for fi, f in enumerate(fields)}
        lines.append(json.dumps({"index": {"_id": str(i)}}))
        lines.append(json.dumps(doc))
    api.handle("POST", "/tier/_bulk", "refresh=true",
               ("\n".join(lines) + "\n").encode())
    svc = api.indices.get("tier")
    svc.plane_cache.repack_mode = "sync"    # inline, deterministic
    svc.plane_cache.lex_prune_min_docs = 1

    def q(field, term):
        st, _ct, payload = api.handle(
            "POST", "/tier/_search", "request_cache=false", json.dumps(
                {"query": {"match": {field: term}}}).encode())
        doc = json.loads(payload)
        assert st == 200 and doc["hits"]["total"]["value"] >= 0
        return doc

    for f in fields:                        # build every plane hot
        q(f, "w3")
    tiers = svc.plane_cache.tiers
    per_plane = {g.field: int(g.base.device_corpus_bytes())
                 for g in svc.plane_cache.generations()}
    total_bytes = sum(per_plane.values())

    # Zipf field mix: rank-1 field owns the head (the hot set), the
    # tail cycles through the demoted planes
    n_queries = 360
    ranks = np.minimum(rng.zipf(1.4, size=n_queries), n_fields) - 1

    def window():
        lat_by_field = {f: [] for f in fields}
        t0 = time.perf_counter()
        for qi in range(n_queries):
            f = fields[int(ranks[qi])]
            t1 = time.perf_counter()
            q(f, vocab[(qi * 5) % 64])
            lat_by_field[f].append(time.perf_counter() - t1)
        wall = time.perf_counter() - t0
        hot = np.asarray(lat_by_field[fields[0]])
        return {"qps": round(n_queries / wall, 1),
                "hot_p99_ms":
                    round(float(np.percentile(hot, 99) * 1e3), 3),
                "hot_n": int(len(hot))}

    device_win = window()                   # baseline: all planes hot

    budget = max(total_bytes // 10, 1)
    tiers.hbm_budget = budget
    tiers.host_budget = max(total_bytes // 4, 1)
    # anti-thrash residency floor (the ES_TPU_PLANE_TIER_MIN_RESIDENCY_S
    # knob): the actively-served Zipf head must not be evicted by every
    # tail promotion — tail planes serve warm/streamed instead
    tiers.min_residency_s = 0.05
    tiers.enforce_budget()                  # demote down to budget
    q(fields[0], "w3")                      # head plane is MRU + hot
    st0 = tiers.stats()
    rb0 = svc.plane_cache.rebuild_stats()
    compiles0 = sum(device_stats_doc().get("compiles", {}).values())
    tiered_win = window()
    st1 = tiers.stats()
    rb1 = svc.plane_cache.rebuild_stats()
    compiles1 = sum(device_stats_doc().get("compiles", {}).values())

    # journal reconstruction: replay plane_tier events into a per-field
    # tier map and cross-check it against the LIVE registry — the
    # acceptance requires transitions be reconstructable from the
    # flight recorder alone
    derived = {}
    for ev in flightrec.DEFAULT.events(type_="plane_tier", limit=4096):
        a = ev.get("attrs", {})
        if a.get("field") in per_plane:
            derived[a["field"]] = a["to_tier"]
    actual = {g.field: g.base.storage_tier
              for g in svc.plane_cache.generations()}
    for rec in tiers.cold_records():
        actual[rec.field] = "cold"
    journal_consistent = all(
        derived.get(f, "hot") == actual.get(f, "hot") for f in fields)

    steady_rebuilds = sum(rb1.get(k, 0) - rb0.get(k, 0)
                          for k in ("cold", "sync", "threshold",
                                    "structure"))
    ratio = tiered_win["hot_p99_ms"] / max(device_win["hot_p99_ms"],
                                           1e-9)
    api.indices.close()
    return _emit("tiered_capacity", {
        "value": tiered_win["qps"], "unit": "queries/s",
        "capacity_ratio": round(total_bytes / budget, 2),
        "hbm_budget_bytes": int(budget),
        "total_plane_bytes": int(total_bytes),
        "hot_p99_ms": tiered_win["hot_p99_ms"],
        "device_p99_ms": device_win["hot_p99_ms"],
        "hot_p99_ratio": round(ratio, 3),
        "hot_n": tiered_win["hot_n"],
        "promotions": st1["promotions"] - st0["promotions"],
        "demotions": st1["demotions"] - st0["demotions"],
        "cold_planes": st1["cold_planes"],
        "warm_planes": st1["warm_planes"],
        "steady_state_rebuilds": int(steady_rebuilds),
        "steady_state_compiles": int(compiles1 - compiles0),
        "journal_consistent": bool(journal_consistent),
        "device_qps": device_win["qps"]})


def bench_qos_overload(rng):
    """Multi-tenant QoS under an abusive tenant (PR 19): one tenant
    floods heavy bulk-class searches from 24 threads while 8 interactive
    tenants keep issuing light point queries through the same
    ``RestAPI.handle`` edge. Three windows:

    - ``unloaded``: interactive tenants alone — the latency baseline.
    - ``protected`` (QoS on, tight per-tenant budget): the abuser's
      post-paid ledger charges drive its bucket into debt → 429s; a
      signal pump feeds REAL batcher queue depth into the shed
      hysteresis so engagement/clear ride actual pressure.
    - ``unprotected`` (``ES_TPU_QOS=0``): same flood with admission
      control off — the collapse the tentpole exists to prevent.

    ``scripts/bench_diff.py`` gates the embedded ``qos`` dict:
    interactive p99 protected ≤ 3× unloaded, shed engaged AND cleared
    per the flight-recorder journal, zero steady-state compiles (the
    priority class must never become a jit shape key)."""
    import tempfile
    import threading
    from elasticsearch_tpu.common import flightrec as _fr
    from elasticsearch_tpu.common import qos as _qos
    from elasticsearch_tpu.common import telemetry as _tm
    from elasticsearch_tpu.node.indices_service import IndicesService
    from elasticsearch_tpu.rest.api import RestAPI
    api = RestAPI(IndicesService(tempfile.mkdtemp(prefix="bench_qos_")))
    vocab = [f"w{i}" for i in range(64)]
    n_docs, lines = 2048, []
    for i in range(n_docs):
        body = " ".join(vocab[(i * 7 + j * 3) % 64] for j in range(8))
        lines.append(json.dumps({"index": {"_id": str(i)}}))
        lines.append(json.dumps({"body": body}))
    api.handle("POST", "/qos/_bulk", "refresh=true",
               ("\n".join(lines) + "\n").encode())
    svc = api.indices.get("qos")

    n_interactive, n_abuser = 8, 24
    lock = threading.Lock()

    def _queue_depth() -> int:
        depth = 0
        for gen in getattr(svc.plane_cache, "_planes", {}).values():
            b = getattr(gen, "_microbatcher", None)
            if b is not None:
                depth += sum(b.queue_depth_by_class().values())
        return depth

    def interactive_client(tid, per, lat, outcomes):
        tenant = f"int-{tid}"
        for j in range(per):
            q = {"query": {"match": {
                "body": vocab[(tid * per + j) % 64]}}}
            t0 = time.perf_counter()
            st, _ct, _payload = api.handle(
                "POST", "/qos/_search", "request_cache=false",
                json.dumps(q).encode(),
                headers={"X-Opaque-Id": tenant})
            dt = time.perf_counter() - t0
            with lock:
                outcomes[st] = outcomes.get(st, 0) + 1
                if st == 200:
                    lat.append(dt)

    def abuser_client(tid, stop_evt, outcomes):
        # heavy bulk-class flood until told to stop: disjunction over 12
        # terms, explicit priority override so the batcher's
        # weighted-deficit picker and the shed verdict both see the bulk
        # class; a 429 backs off briefly (a real client would honor
        # Retry-After — hammering with zero sleep would measure spin
        # contention, not admission control)
        j = 0
        while not stop_evt.is_set():
            j += 1
            q = {"query": {"bool": {"should": [
                {"match": {"body": vocab[(tid + j + s) % 64]}}
                for s in range(12)]}}}
            st, _ct, _payload = api.handle(
                "POST", "/qos/_search", "request_cache=false",
                json.dumps(q).encode(),
                headers={"X-Opaque-Id": "abuser",
                         "x-es-priority": "bulk"})
            with lock:
                outcomes[st] = outcomes.get(st, 0) + 1
            if st == 429:
                time.sleep(0.02)

    def run_window(per_interactive, flood=False, pump=False,
                   wait_debt=False):
        lat, int_out, ab_out = [], {}, {}
        stop_pump, stop_flood = threading.Event(), threading.Event()

        def signal_pump():
            ctl = _qos.controller()
            while not stop_pump.is_set():
                ctl.note_signals(queue_depth=_queue_depth())
                time.sleep(0.001)

        pump_t = None
        if pump:
            pump_t = threading.Thread(target=signal_pump, daemon=True)
            pump_t.start()
        ab_threads = [threading.Thread(target=abuser_client,
                                       args=(t, stop_flood, ab_out))
                      for t in range(n_abuser)] if flood else []
        for t in ab_threads:
            t.start()
        if wait_debt:
            # untimed flood preamble: wait for the abuser's post-paid
            # ledger charges to drive its bucket into debt, so the timed
            # interactive window measures STEADY-STATE protection (the
            # burst the bucket legitimately admits is not "overload");
            # the pump meanwhile sees the pre-debt queue pressure
            ctl = _qos.controller()
            deadline = time.perf_counter() + 10.0
            while ctl.tokens("abuser") >= 0.0 \
                    and time.perf_counter() < deadline:
                time.sleep(0.002)
        threads = [threading.Thread(target=interactive_client,
                                    args=(t, per_interactive, lat,
                                          int_out))
                   for t in range(n_interactive)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        stop_flood.set()
        for t in ab_threads:
            t.join()
        if pump_t is not None:
            # flood is over: let the pump observe the drained queue so
            # the clear transition lands in the journal, then stop it
            time.sleep(0.05)
            stop_pump.set()
            pump_t.join(timeout=1.0)
        a = np.asarray(lat) if lat else np.asarray([0.0])
        return {"interactive_qps": round(len(lat) / wall, 1),
                "p50_ms": round(float(np.percentile(a, 50) * 1e3), 2),
                "p99_ms": round(float(np.percentile(a, 99) * 1e3), 2),
                "interactive_by_status": dict(sorted(int_out.items())),
                "abuser_by_status": dict(sorted(ab_out.items()))}

    # per-tenant budget sized so burst alone covers one interactive
    # tenant's whole window (~500 cost units) — interactive tenants
    # never throttle — while the abuser's ACTUAL ledger charges
    # (cpu-ms + weighted device-ms, post-paid at task completion) blow
    # through burst during the flood preamble; the small refill keeps
    # the post-debt abuser to a trickle so re-admission bursts (and the
    # severe-shed oscillation they cause) stay rare; shed threshold low
    # enough that real queue pressure from the pre-debt burst trips it
    knobs = {"ES_TPU_QOS_REFILL_PER_S": "60",
             "ES_TPU_QOS_BURST": "800",
             "ES_TPU_QOS_SHED_QUEUE_DEPTH": "4",
             "ES_TPU_QOS_RETRY_AFTER_S": "0.05"}
    prev = {k: os.environ.get(k) for k in list(knobs) + ["ES_TPU_QOS"]}
    try:
        os.environ.update(knobs)
        # warm round with the EXACT timed mix (both tenants, both
        # priority classes, same concurrency) and QoS OFF so the
        # unthrottled flood compiles every pow2 batch bucket both query
        # shapes can produce — any compile after this is a shape leak
        os.environ["ES_TPU_QOS"] = "0"
        run_window(4, flood=True)

        os.environ["ES_TPU_QOS"] = "1"
        _qos.reset_controller()
        compiles0 = _tm.compile_count()
        unloaded = run_window(24)

        _qos.reset_controller()
        evs = _fr.DEFAULT.events(type_="qos_shed", limit=0)
        seq0 = evs[-1]["seq"] if evs else 0
        protected = run_window(24, flood=True, pump=True,
                               wait_debt=True)
        ctl_doc = _qos.controller().status_doc()
        evs = [e for e in _fr.DEFAULT.events(type_="qos_shed", limit=0)
               if e["seq"] > seq0]
        transitions = [e["attrs"].get("transition") for e in evs
                       if "transition" in e["attrs"]]

        os.environ["ES_TPU_QOS"] = "0"
        _qos.reset_controller()
        unprotected = run_window(24, flood=True)
        steady_compiles = _tm.compile_count() - compiles0
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        _qos.reset_controller()
    api.indices.close()

    ratio = protected["p99_ms"] / max(unloaded["p99_ms"], 1e-9)
    return _emit("qos_overload", {
        "value": protected["interactive_qps"], "unit": "requests/s",
        "p99_ms": protected["p99_ms"],
        "n_interactive_clients": n_interactive,
        "n_abuser_clients": n_abuser,
        "unloaded": unloaded,
        "protected": protected,
        "unprotected": unprotected,
        "qos": {
            "interactive_p99_unloaded_ms": unloaded["p99_ms"],
            "interactive_p99_protected_ms": protected["p99_ms"],
            "interactive_p99_unprotected_ms": unprotected["p99_ms"],
            "protected_over_unloaded": round(ratio, 3),
            "shed_engaged": "engage" in transitions,
            "shed_cleared": transitions[-1] == "clear"
            if transitions else False,
            "engagements": ctl_doc["engagements"],
            "cleared_total": ctl_doc["cleared_total"],
            "sheds_total": ctl_doc["sheds_total"],
            "throttled_total": ctl_doc["throttled_total"],
            "admitted_total": ctl_doc["admitted_total"],
            "steady_compiles": int(steady_compiles),
        }})


def workload_L(plane, batches, Q=None):
    """One compile shape per config, sized to the WORKLOAD's largest
    sparse posting run instead of the table-wide L_cap — the merge cost
    scales with L, and frequency-weighted queries mostly hit dense-tier
    terms whose sparse runs are empty."""
    from elasticsearch_tpu.utils.shapes import round_up_pow2
    max_len = 1
    for qs in batches:
        max_len = max(max_len, plane.max_run_len(qs))
    return min(round_up_pow2(max_len), plane.L_cap)

def main(mode: str = "accel"):
    import jax
    if mode == "cpu" or os.environ.get("BENCH_FORCE_CPU"):
        # the ambient sitecustomize registers the accelerator backend and env
        # vars alone can't override it — go through jax.config
        jax.config.update("jax_platforms", "cpu")
    # persistent compilation cache: recompiles over the tunnel cost
    # minutes per run; cached executables survive into the driver's
    # end-of-round invocation
    if mode != "cpu" and not os.environ.get("BENCH_FORCE_CPU"):
        # accel only: recompiles over the tunnel cost minutes per run
        # and the cache halves the next run's setup. CPU children skip
        # it — their compiles are seconds, and this XLA version's CPU
        # AOT loader logs feature-mismatch warnings on every cache load
        # (virtual +prefer-no-* features baked at compile time).
        try:
            cache_dir = os.path.join(os.path.dirname(os.path.abspath(
                __file__)), ".jax_cache", "accel")
            os.makedirs(cache_dir, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception as e:   # noqa: BLE001 — cache is best-effort
            print(f"# compilation cache unavailable: {e}",
                  file=sys.stderr)
    devs = jax.devices()
    print(f"# jax backend: {devs[0].platform} x{len(devs)}", file=sys.stderr)
    from elasticsearch_tpu.parallel import (DistributedSearchPlane,
                                            make_search_mesh)
    from elasticsearch_tpu.utils.synth import (split_csr_shards,
                                               synthetic_csr_corpus_fast)

    on_cpu = devs[0].platform == "cpu"
    n_docs = int(os.environ.get("BENCH_N_DOCS", 0)) or \
        ((1 << 18) if on_cpu else (1 << 23))

    # --configs substring filter (BENCH_CONFIGS env for child procs):
    # run only matching configs — e.g. `--configs lexical_10m_prune`
    # runs the 4M-doc pruning config alone without paying the full suite
    filt = os.environ.get("BENCH_CONFIGS", "").strip()

    def want(name: str) -> bool:
        return not filt or filt in name

    need_plane = any(want(n) for n in
                     ("match_bm25_headline", "batch_curve",
                      "bool_disjunction"))
    rng = np.random.RandomState(1234)
    n_dev = len(jax.devices())
    mesh = make_search_mesh(n_shards=n_dev, n_replicas=1)
    corpus = plane = None
    cpu_qps = 0.0
    if need_plane:
        t0 = time.perf_counter()
        corpus = synthetic_csr_corpus_fast(rng, n_docs, VOCAB, AVG_DL,
                                           zipf_s=1.2)
        corpus["term_ids"] = {f"t{t}": t for t in range(VOCAB)}
        print(f"# corpus: {n_docs} docs, {corpus['docs'].shape[0]} postings "
              f"({time.perf_counter() - t0:.1f}s)", file=sys.stderr)

        # ---- CPU reference ------------------------------------------------
        cpu_queries = sample_queries(rng, corpus, 1,
                                     batch=CPU_REF_QUERIES)[0]
        cpu_times, _ = cpu_bm25_search(corpus, cpu_queries, K)
        cpu_qps = len(cpu_times) / sum(cpu_times)
        print(f"# cpu ref: {cpu_qps:.1f} qps, "
              f"p99 {np.percentile(cpu_times, 99) * 1e3:.1f} ms",
              file=sys.stderr)

        # ---- TPU ----------------------------------------------------------
        t0 = time.perf_counter()
        shards = split_csr_shards(corpus, n_dev) if n_dev > 1 else [corpus]
        for s in shards:
            s["term_ids"] = corpus["term_ids"]
        plane = DistributedSearchPlane(mesh, shards, field="body")
        print(f"# plane: {plane.n_shards} shards, n_pad {plane.n_pad}, "
              f"dense tier T={plane.n_dense} (pad {plane.T_pad}), "
              f"sparse L_cap {plane.L_cap} "
              f"({time.perf_counter() - t0:.1f}s)", file=sys.stderr)

    # fixed compile shapes: Q=N_TERMS, workload-sized L, tiered kernel.
    # On a CPU backend the serving path is the plane's term-at-a-time eager
    # scorer (search_eager — the matmul dense tier exists to ride the MXU
    # and does ~25x the arithmetic a CPU should do); the tiered kernel is
    # still timed and reported as kernel_cpu_qps for transparency.
    on_cpu_serving = on_cpu
    kernel_cpu_qps = None
    tpu_qps = p99_ms = 0.0
    lat = np.zeros(1)
    if need_plane:
        tiered = plane.T_pad > 0
        warm = sample_queries(rng, corpus, 1)[0]
        timed_batches = sample_queries(rng, corpus, TIMED_ITERS)
        kb = sample_queries(rng, corpus, 8) if on_cpu else []
        t0 = time.perf_counter()
        L1 = workload_L(plane, [warm] + timed_batches + kb, N_TERMS)
        print(f"# headline L (workload-sized): {L1} (cap {plane.L_cap})",
              file=sys.stderr)
        plane.search(warm, k=K, Q=N_TERMS, L=L1, tiered=tiered)
        print(f"# compile+warm: {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)

        if on_cpu_serving:
            t0 = time.perf_counter()
            for qs in kb:
                plane.search(qs, k=K, Q=N_TERMS, L=L1, tiered=tiered)
            kernel_cpu_qps = (8 * BATCH) / (time.perf_counter() - t0)
            print(f"# tiered kernel on cpu: {kernel_cpu_qps:.1f} qps "
                  f"(reported as kernel_cpu_qps)", file=sys.stderr)
            plane.search_eager(warm, k=K)       # warm the eager path

        lat = []
        first_result = None
        for qs in timed_batches:
            t0 = time.perf_counter()
            if on_cpu_serving:
                vals, hits = plane.search_eager(qs, k=K)
            else:
                vals, hits = plane.search(qs, k=K, Q=N_TERMS, L=L1,
                                          tiered=tiered)
            lat.append(time.perf_counter() - t0)
            if first_result is None:
                first_result = (qs, vals)
        lat = np.asarray(lat)
        tpu_qps = (TIMED_ITERS * BATCH) / lat.sum()
        p99_ms = float(np.percentile(lat, 99) * 1e3)

        # correctness cross-check: the first dispatch's top-1 scores must
        # match the CPU reference within f32/bf16 tolerance — a kernel
        # regression must fail the bench, not report a healthy QPS (run on
        # 4 queries; the CPU reference costs ~0.3 s/query at this size)
        qs, vals = first_result
        _, cpu_hits = cpu_bm25_search(corpus, qs[:4], K)
        for bi in range(4):
            cpu_top = cpu_hits[bi][0]
            cpu_score = _score_one(corpus, qs[bi], int(cpu_top))
            tpu_score = float(vals[bi][0])
            if abs(tpu_score - cpu_score) > 0.02 * max(1.0, abs(cpu_score)):
                raise SystemExit(
                    f"correctness check failed: query {qs[bi]} TPU top "
                    f"score {tpu_score} vs CPU {cpu_score}")
        print("# correctness cross-check vs CPU reference: OK",
              file=sys.stderr)

    configs = {}
    if need_plane:
        _emit("match_bm25_headline", {
            "value": round(tpu_qps, 1), "unit": "queries/s",
            "vs_baseline": round(tpu_qps / cpu_qps, 2),
            "p99_ms": round(p99_ms, 2)})

    def run(name, fn, *args):
        if not want(name):
            return
        eff0 = _efficiency_snapshot()
        try:
            configs[name] = fn(*args)
        except SystemExit:
            raise
        except Exception as e:                     # noqa: BLE001 — a broken
            # secondary config must not cost the headline number
            configs[name] = {"error": repr(e)[:300]}
            print(f"# config {name} FAILED: {e!r}", file=sys.stderr)
        if isinstance(configs.get(name), dict) and \
                "error" not in configs[name]:
            # roofline audit delta for THIS config's dispatches: per
            # kernel family, how many were audited and their mean
            # model-vs-achieved efficiency (bench_diff gates a >20%
            # per-kernel drop on paired configs)
            eff = _efficiency_delta(eff0)
            if eff:
                configs[name]["efficiency"] = eff

    if need_plane:
        run("batch_curve", bench_batch_curve, rng, corpus, plane, on_cpu)
        run("bool_disjunction", bench_bool_disjunction, rng, corpus,
            plane, on_cpu)
        del plane
    run("terms_percentiles", bench_terms_percentiles, rng, on_cpu)
    run("knn", bench_knn, rng, mesh, on_cpu)
    run("knn_ivf_recall", bench_knn_ivf, rng, mesh, on_cpu)
    if on_cpu:
        # host-serving config: the pruned/eager split it measures is the
        # CPU path (search_pruned_eager vs search_eager); on an
        # accelerator the dense matmul tier already owns the Zipf head
        # and the fixed-trip masked scan would measure compile shape,
        # not pruning
        run("lexical_10m_prune", bench_lexical_prune, rng, mesh, on_cpu)
    run("hybrid_rrf", bench_hybrid_rrf, rng, mesh, on_cpu)
    run("hybrid_rrf_fused", bench_hybrid_rrf_fused, rng, on_cpu)
    run("analytics_fused", bench_analytics_fused, rng, on_cpu)
    run("serving", bench_serving, rng)
    run("live_indexing", bench_live_indexing, rng)
    run("tiered_capacity", bench_tiered_capacity, rng)
    run("qos_overload", bench_qos_overload, rng)

    if not need_plane:
        # filtered run without the headline: promote the first selected
        # config's number so the final JSON line still carries a metric
        first = next((c for c in configs.values()
                      if isinstance(c, dict) and "value" in c), {})
        tpu_qps = float(first.get("value", 0.0))
        p99_ms = float(first.get("p99_ms", 0.0))
    doc = {
        "metric": f"bm25_topk_qps_{n_docs}_docs_uncapped_df"
        if need_plane else f"filtered[{filt}]",
        "value": round(tpu_qps, 1),
        "unit": "queries/s",
        "vs_baseline": round(tpu_qps / cpu_qps, 2) if cpu_qps else None,
        "p99_ms": round(p99_ms, 2),
        "p50_ms": round(float(np.percentile(lat, 50) * 1e3), 2),
        "max_ms": round(float(lat.max() * 1e3), 2),
        "n_dispatches": TIMED_ITERS,
        "cpu_ref_qps": round(cpu_qps, 1),
        "n_devices": n_dev,
        # a CPU-fallback run must be distinguishable from a real TPU result
        "backend": jax.devices()[0].platform,
        "configs": configs,
        # end-of-run registry rollup: compile counts + device bytes moved
        "telemetry": _telemetry_snapshot(),
        # false-positive invariant: a steady-state bench run must never
        # trip the SLO watchdog (bench_diff gates nonzero as a
        # regression); manual/seeded captures are excluded
        "watchdog_steady_captures": _watchdog_steady_captures(),
        # whole-run roofline audit rollup (model vs achieved per kernel
        # family — the ROOFLINE.md measured-efficiency table's source)
        "dispatch_efficiency": _efficiency_delta({}),
    }
    if kernel_cpu_qps is not None:
        doc["serving_path"] = "eager-cpu"
        doc["kernel_cpu_qps"] = round(kernel_cpu_qps, 1)
    print(json.dumps(doc))


if __name__ == "__main__":
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", nargs="?", const="accel", default=None)
    ap.add_argument("--configs", default=None,
                    help="substring filter: run only configs whose name "
                         "contains this (e.g. lexical_10m_prune)")
    args, _unknown = ap.parse_known_args()
    if args.configs:
        # children inherit the filter through the environment
        os.environ["BENCH_CONFIGS"] = args.configs
    if args.child is not None:
        main(args.child)
    else:
        orchestrate()
