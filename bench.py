"""Headline benchmark: batched BM25 top-k QPS + p99 latency, TPU vs CPU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Workload (BASELINE.md eval config #1 shape, synthetic stand-in for MS MARCO
since the image has no dataset): 2^23 (~8.4M) Zipf-distributed docs, batched
bag-of-words queries, k=10. Query terms are drawn **term-frequency-weighted
with no df cap** — Zipf-head (stop-word-df) terms appear in queries at their
natural rate and are scored exactly by the tiered kernel
(``ops/tiered_bm25.py``: dense-tier streaming matmul + sparse sorted-merge).

``vs_baseline`` is TPU QPS / CPU QPS where the CPU reference is a vectorized
numpy CSR BM25 (per-term gather + scatter-add + argpartition top-k — the
same eager-scoring algorithm, honestly tuned for CPU; it stands in for
Lucene's BulkScorer loop, ``search/internal/ContextIndexSearcher.java:
210-224``, which is not available in this image).

p99 is per-query latency in the batched serving model: every query's latency
is its dispatch's wall time (host assembly + device step + result sync),
measured over TIMED_ITERS independent dispatches.

On >1 device the corpus splits into per-device doc-range shards and the
query batch runs SPMD over the (replica, shard) mesh; on the single tunneled
TPU chip it runs one-shard. BENCH_FORCE_CPU=1 runs a scaled-down CPU-mesh
variant (clearly labeled via "backend").
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

VOCAB = 1 << 16
AVG_DL = 32
BATCH = 64                 # queries per dispatch
N_TERMS = 4                # terms per query
K = 10
TIMED_ITERS = 128          # percentile sample size: p99 interpolates near
                           # the top sample, so keep the pool deep enough
CPU_REF_QUERIES = 32       # CPU reference is ~0.2 s/query at 8.4M docs
K1, B = 1.2, 0.75


# ---------------------------------------------------------------------------
# Backend orchestration (parent process — NEVER touches a jax backend itself)
#
# Rounds 1 and 2 produced no perf number because jax backend init against the
# tunneled accelerator sometimes HANGS instead of throwing: an in-process
# retry loop around jax.devices() (the round-2 fix) blocks forever on attempt
# 2 and the driver's outer timeout kills the whole script (rc=124, no JSON).
# The only robust shape is process isolation: probe the backend in a
# subprocess with a hard wall-clock timeout, run the bench itself in a
# timeboxed subprocess, and fall back to forced-CPU (proven to work — the
# test suite runs on it) or, last resort, a pure-numpy measurement.
# A final JSON line is emitted UNCONDITIONALLY.
# ---------------------------------------------------------------------------

PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT", 120))
ACCEL_BENCH_TIMEOUT_S = int(os.environ.get("BENCH_ACCEL_TIMEOUT", 700))
CPU_BENCH_TIMEOUT_S = int(os.environ.get("BENCH_CPU_TIMEOUT", 500))

_PROBE_SRC = (
    "import jax; d = jax.devices(); print(d[0].platform, len(d), flush=True)"
)


PROBE_LOG: list = []          # every attempt's outcome, emitted in the JSON


def _probe_backend(attempts: int = 3, stagger_s: int = 15) -> str | None:
    """Ask a throwaway subprocess what jax backend comes up, with a hard
    timeout per attempt and a stagger between attempts (the tunnel hang is
    intermittent across rounds: r01 threw, r02/r03 hung — an init that
    fails now may succeed seconds later). Returns the platform string or
    None; every attempt's outcome lands in PROBE_LOG for the final JSON."""
    for i in range(attempts):
        if i:
            time.sleep(stagger_s)
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True, text=True, timeout=PROBE_TIMEOUT_S,
            )
            if r.returncode == 0 and r.stdout.strip():
                plat, ndev = r.stdout.split()[:2]
                print(f"# backend probe: {plat} x{ndev}", file=sys.stderr)
                PROBE_LOG.append(f"ok:{plat}x{ndev}")
                return plat
            PROBE_LOG.append(f"rc={r.returncode}")
            print(f"# backend probe attempt {i + 1}/{attempts} rc="
                  f"{r.returncode}: {r.stderr.strip()[-300:]}",
                  file=sys.stderr)
        except subprocess.TimeoutExpired:
            PROBE_LOG.append(f"timeout{PROBE_TIMEOUT_S}s")
            print(f"# backend probe attempt {i + 1}/{attempts} timed out "
                  f"after {PROBE_TIMEOUT_S}s (hung init)", file=sys.stderr)
    return None


def _run_child(mode: str, timeout_s: int) -> str | None:
    """Run `bench.py --child <mode>` under a hard timeout; return its final
    JSON stdout line, or None on timeout/failure."""
    print(f"# launching bench child mode={mode} timeout={timeout_s}s",
          file=sys.stderr)
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child", mode],
            stdout=subprocess.PIPE, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        print(f"# bench child ({mode}) timed out after {timeout_s}s",
              file=sys.stderr)
        return None
    line = None
    for ln in (r.stdout or "").splitlines():
        ln = ln.strip()
        if ln.startswith("{") and ln.endswith("}"):
            line = ln
    if r.returncode != 0:
        print(f"# bench child ({mode}) rc={r.returncode}", file=sys.stderr)
        return None
    if line is None:
        print(f"# bench child ({mode}) emitted no JSON line", file=sys.stderr)
    return line


def _numpy_last_resort() -> None:
    """No usable jax backend at all: measure the numpy CSR reference alone so
    the driver still records a real (clearly labeled) number."""
    rng = np.random.RandomState(1234)
    from elasticsearch_tpu.utils.synth import synthetic_csr_corpus_fast
    n_docs = 1 << 16
    corpus = synthetic_csr_corpus_fast(rng, n_docs, VOCAB, AVG_DL, zipf_s=1.2)
    queries = sample_queries(rng, corpus, 1, batch=CPU_REF_QUERIES)[0]
    times, _ = cpu_bm25_search(corpus, queries, K)
    qps = len(times) / sum(times)
    print(json.dumps({
        "metric": f"bm25_topk_qps_{n_docs}_docs_uncapped_df",
        "value": round(qps, 1),
        "unit": "queries/s",
        "vs_baseline": 1.0,
        "p99_ms": round(float(np.percentile(times, 99) * 1e3), 2),
        "cpu_ref_qps": round(qps, 1),
        "n_devices": 0,
        "backend": "numpy-fallback-no-jax",
        "probe_attempts": PROBE_LOG,
    }))


def orchestrate() -> None:
    plan: list[tuple[str, int]] = []
    if not os.environ.get("BENCH_FORCE_CPU"):
        plat = _probe_backend()
        if plat is not None and plat != "cpu":
            plan.append(("accel", ACCEL_BENCH_TIMEOUT_S))
    plan.append(("cpu", CPU_BENCH_TIMEOUT_S))
    for mode, tmo in plan:
        line = _run_child(mode, tmo)
        if line is not None:
            try:
                doc = json.loads(line)
                doc["probe_attempts"] = PROBE_LOG
                line = json.dumps(doc)
            except ValueError:
                pass
            print(line, flush=True)
            return
    _numpy_last_resort()


def sample_queries(rng, corpus, n_batches, batch=BATCH):
    """Term-frequency-weighted query sampling, NO df cap: term t is drawn
    with probability ∝ its posting mass, like sampling words from real query
    logs — head terms (df ≈ N) appear constantly."""
    df = corpus["df"].astype(np.float64)
    eligible = np.flatnonzero(df >= 2)
    p = df[eligible] / df[eligible].sum()
    batches = []
    for _ in range(n_batches):
        draws = rng.choice(eligible, size=(batch, N_TERMS), p=p)
        batches.append([[f"t{t}" for t in row] for row in draws])
    return batches


def cpu_bm25_search(corpus, queries, k):
    """Vectorized numpy CSR BM25 + argpartition top-k (CPU reference).
    Returns (per-query seconds list, hits)."""
    offsets, docs, tf = corpus["offsets"], corpus["docs"], corpus["tf"]
    dl = corpus["doc_len"]
    n_docs = dl.shape[0]
    avgdl = dl.mean()
    df = corpus["df"]
    out, times = [], []
    for terms in queries:
        t0 = time.perf_counter()
        scores = np.zeros(n_docs, np.float32)
        for t in set(terms):
            tid = int(t[1:])
            st, en = offsets[tid], offsets[tid + 1]
            if en == st:
                continue
            run_docs = docs[st:en]
            run_tf = tf[st:en]
            idf = np.log(1 + (n_docs - df[tid] + 0.5) / (df[tid] + 0.5))
            w = terms.count(t)
            norm = run_tf + K1 * (1 - B + B * dl[run_docs] / avgdl)
            scores[run_docs] += w * idf * (K1 + 1) * run_tf / norm
        top = np.argpartition(-scores, k)[:k]
        out.append(top[np.argsort(-scores[top], kind="stable")])
        times.append(time.perf_counter() - t0)
    return times, out


def _score_one(corpus, terms, doc: int) -> float:
    """Exact CPU BM25 of one (query, doc) pair — the cross-check oracle."""
    offsets, docs, tf = corpus["offsets"], corpus["docs"], corpus["tf"]
    dl = corpus["doc_len"]
    n_docs = dl.shape[0]
    avgdl = dl.mean()
    s = 0.0
    for t in set(terms):
        tid = int(t[1:])
        st, en = offsets[tid], offsets[tid + 1]
        run = docs[st:en]
        i = np.searchsorted(run, doc)
        if i >= run.shape[0] or run[i] != doc:
            continue
        f = float(tf[st + i])
        idf = float(np.log(1 + (n_docs - corpus["df"][tid] + 0.5)
                           / (corpus["df"][tid] + 0.5)))
        s += terms.count(t) * idf * (K1 + 1) * f / (
            f + K1 * (1 - B + B * float(dl[doc]) / avgdl))
    return s


def main(mode: str = "accel"):
    import jax
    if mode == "cpu" or os.environ.get("BENCH_FORCE_CPU"):
        # the ambient sitecustomize registers the accelerator backend and env
        # vars alone can't override it — go through jax.config
        jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()
    print(f"# jax backend: {devs[0].platform} x{len(devs)}", file=sys.stderr)
    from elasticsearch_tpu.parallel import (DistributedSearchPlane,
                                            make_search_mesh)
    from elasticsearch_tpu.utils.synth import (split_csr_shards,
                                               synthetic_csr_corpus_fast)

    on_cpu = devs[0].platform == "cpu"
    n_docs = int(os.environ.get("BENCH_N_DOCS", 0)) or \
        ((1 << 18) if on_cpu else (1 << 23))

    rng = np.random.RandomState(1234)
    t0 = time.perf_counter()
    corpus = synthetic_csr_corpus_fast(rng, n_docs, VOCAB, AVG_DL,
                                       zipf_s=1.2)
    corpus["term_ids"] = {f"t{t}": t for t in range(VOCAB)}
    print(f"# corpus: {n_docs} docs, {corpus['docs'].shape[0]} postings "
          f"({time.perf_counter() - t0:.1f}s)", file=sys.stderr)

    # ---- CPU reference ----------------------------------------------------
    cpu_queries = sample_queries(rng, corpus, 1, batch=CPU_REF_QUERIES)[0]
    cpu_times, _ = cpu_bm25_search(corpus, cpu_queries, K)
    cpu_qps = len(cpu_times) / sum(cpu_times)
    print(f"# cpu ref: {cpu_qps:.1f} qps, "
          f"p99 {np.percentile(cpu_times, 99) * 1e3:.1f} ms", file=sys.stderr)

    # ---- TPU --------------------------------------------------------------
    n_dev = len(jax.devices())
    mesh = make_search_mesh(n_shards=n_dev, n_replicas=1)
    t0 = time.perf_counter()
    shards = split_csr_shards(corpus, n_dev) if n_dev > 1 else [corpus]
    for s in shards:
        s["term_ids"] = corpus["term_ids"]
    plane = DistributedSearchPlane(mesh, shards, field="body")
    print(f"# plane: {plane.n_shards} shards, n_pad {plane.n_pad}, "
          f"dense tier T={plane.n_dense} (pad {plane.T_pad}), "
          f"sparse L_cap {plane.L_cap} "
          f"({time.perf_counter() - t0:.1f}s)", file=sys.stderr)

    # fixed compile shapes: Q=N_TERMS, L=L_cap, tiered kernel throughout.
    # On a CPU backend the serving path is the plane's term-at-a-time eager
    # scorer (search_eager — the matmul dense tier exists to ride the MXU
    # and does ~25x the arithmetic a CPU should do); the tiered kernel is
    # still timed and reported as kernel_cpu_qps for transparency.
    on_cpu_serving = on_cpu
    tiered = plane.T_pad > 0
    warm = sample_queries(rng, corpus, 1)[0]
    t0 = time.perf_counter()
    plane.search(warm, k=K, Q=N_TERMS, L=plane.L_cap, tiered=tiered)
    print(f"# compile+warm: {time.perf_counter() - t0:.1f}s", file=sys.stderr)

    kernel_cpu_qps = None
    if on_cpu_serving:
        kb = sample_queries(rng, corpus, 8)
        t0 = time.perf_counter()
        for qs in kb:
            plane.search(qs, k=K, Q=N_TERMS, L=plane.L_cap, tiered=tiered)
        kernel_cpu_qps = (8 * BATCH) / (time.perf_counter() - t0)
        print(f"# tiered kernel on cpu: {kernel_cpu_qps:.1f} qps "
              f"(reported as kernel_cpu_qps)", file=sys.stderr)
        plane.search_eager(warm, k=K)       # warm the eager path

    timed_batches = sample_queries(rng, corpus, TIMED_ITERS)
    lat = []
    first_result = None
    for qs in timed_batches:
        t0 = time.perf_counter()
        if on_cpu_serving:
            vals, hits = plane.search_eager(qs, k=K)
        else:
            vals, hits = plane.search(qs, k=K, Q=N_TERMS, L=plane.L_cap,
                                      tiered=tiered)
        lat.append(time.perf_counter() - t0)
        if first_result is None:
            first_result = (qs, vals)
    lat = np.asarray(lat)
    tpu_qps = (TIMED_ITERS * BATCH) / lat.sum()
    p99_ms = float(np.percentile(lat, 99) * 1e3)

    # correctness cross-check: the first dispatch's top-1 scores must match
    # the CPU reference within f32/bf16 tolerance — a kernel regression
    # must fail the bench, not report a healthy QPS (run on 4 queries; the
    # CPU reference costs ~0.3 s/query at this corpus size)
    qs, vals = first_result
    _, cpu_hits = cpu_bm25_search(corpus, qs[:4], K)
    for bi in range(4):
        cpu_top = cpu_hits[bi][0]
        cpu_score = _score_one(corpus, qs[bi], int(cpu_top))
        tpu_score = float(vals[bi][0])
        if abs(tpu_score - cpu_score) > 0.02 * max(1.0, abs(cpu_score)):
            raise SystemExit(
                f"correctness check failed: query {qs[bi]} TPU top score "
                f"{tpu_score} vs CPU {cpu_score}")
    print("# correctness cross-check vs CPU reference: OK",
          file=sys.stderr)

    doc = {
        "metric": f"bm25_topk_qps_{n_docs}_docs_uncapped_df",
        "value": round(tpu_qps, 1),
        "unit": "queries/s",
        "vs_baseline": round(tpu_qps / cpu_qps, 2),
        "p99_ms": round(p99_ms, 2),
        "p50_ms": round(float(np.percentile(lat, 50) * 1e3), 2),
        "max_ms": round(float(lat.max() * 1e3), 2),
        "n_dispatches": TIMED_ITERS,
        "cpu_ref_qps": round(cpu_qps, 1),
        "n_devices": n_dev,
        # a CPU-fallback run must be distinguishable from a real TPU result
        "backend": jax.devices()[0].platform,
    }
    if kernel_cpu_qps is not None:
        doc["serving_path"] = "eager-cpu"
        doc["kernel_cpu_qps"] = round(kernel_cpu_qps, 1)
    print(json.dumps(doc))


if __name__ == "__main__":
    if len(sys.argv) >= 2 and sys.argv[1] == "--child":
        main(sys.argv[2] if len(sys.argv) > 2 else "accel")
    else:
        orchestrate()
