#!/usr/bin/env python
"""Multichip serving measurement at ONE virtual-device count.

One process per device count: the XLA host-platform device count is
fixed per process (``--xla_force_host_platform_device_count`` is read at
backend init), so ``__graft_entry__.dryrun_multichip`` runs this script
once per point of its 1/2/4/8 sweep and compares the JSON docs the runs
print. Everything here runs the SHARDED DEVICE serving path — the
host-native CPU scorers are disabled (``ES_TPU_PLANE_HOST_SERVE=0``)
because they bypass the mesh entirely, and the sweep exists to measure
the mesh.

The corpus is a FIXED 8-segment synthetic build (seeded), identical at
every device count, so per-query results must be bit-identical across
mesh shapes (the kernels partition shards over devices but never change
per-shard scoring or the (score desc, doc asc) merge order) and the
parent asserts exact equality against the 1-device run. Reported
per-device corpus bytes are MEASURED from the live device buffers
(``addressable_shards``), not derived from the mesh shape.

Usage:  python scripts/bench_multichip.py --devices 4 [--replicas 2]
Prints one JSON doc on stdout (last line).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

# -- corpus/workload constants: identical at every device count ------------
# Sized so the dispatch is corpus-bandwidth-bound (BM25S's regime — the
# scan streams ~n_pad accumulator + postings bytes per shard): small
# corpora measure XLA's per-device dispatch overhead instead of the
# sharding, and multi-device goes NEGATIVE there. At 32k docs/segment
# the 8-device dispatch is ~1.45x the 1-device rate on this backend.
N_SEGMENTS = 8          # divides every swept device count (1/2/4/8)
DOCS_PER_SEGMENT = 32768
VOCAB = 2048
AVG_DL = 16
KNN_DOCS_PER_SEGMENT = 2048
KNN_DIM = 32
K = 10
EVAL_B = 16             # parity batch (one fixed plane.search call)
N_CLIENTS = 8           # throughput window client threads
PER_CLIENT = 24


def _force_devices(n: int) -> None:
    """Pin the virtual CPU platform BEFORE jax initializes a backend."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    os.environ["XLA_FLAGS"] = " ".join(
        flags + [f"--xla_force_host_platform_device_count={n}"])
    # the whole point is the sharded device path — never the host scorers
    os.environ["ES_TPU_PLANE_HOST_SERVE"] = "0"


def _eval_queries(rng, plane_vocab: int):
    """Fixed bag-of-terms eval batch: mixed run lengths, some repeated
    terms, all within one ladder rung family."""
    qs = []
    for i in range(EVAL_B):
        n_terms = 2 + (i % 3)
        qs.append([f"t{int(rng.randint(8, plane_vocab // 4))}"
                   for _ in range(n_terms)])
    return qs


def _measured_device_bytes(arrays) -> int:
    """Max per-device resident bytes over the given jax arrays, read from
    the live buffers — the ground truth the accessor estimates."""
    per_dev: dict = {}
    for a in arrays:
        if a is None:
            continue
        for s in a.addressable_shards:
            did = int(s.device.id)
            per_dev[did] = per_dev.get(did, 0) + int(s.data.nbytes)
    return max(per_dev.values()) if per_dev else 0


def _compiles_total(tm) -> int:
    doc = tm.DEFAULT.metrics_doc().get("es_xla_compiles_total")
    if not doc:
        return 0
    return int(sum(s["value"] for s in doc["series"]))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, required=True)
    ap.add_argument("--replicas", type=int, default=1)
    args = ap.parse_args()
    n_dev = int(args.devices)
    n_repl = max(int(args.replicas), 1)
    if n_dev % n_repl:
        raise SystemExit(f"--replicas {n_repl} must divide --devices {n_dev}")
    _force_devices(n_dev)
    # the serving cache default (mesh_from_env) is what's under test:
    # drive it through the same env knobs production uses
    os.environ["ES_TPU_MESH_REPLICAS"] = str(n_repl)
    os.environ["ES_TPU_MESH_SHARDS"] = str(n_dev // n_repl)

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import numpy as np
    import jax

    if len(jax.devices()) < n_dev or jax.devices()[0].platform != "cpu":
        raise SystemExit(
            f"needed {n_dev} virtual CPU devices, jax sees "
            f"{len(jax.devices())} {jax.devices()[0].platform}")

    from elasticsearch_tpu.common import telemetry as tm
    from elasticsearch_tpu.parallel import (DistributedKnnPlane,
                                            DistributedSearchPlane,
                                            mesh_from_env)
    from elasticsearch_tpu.parallel.mesh import AXIS_REPLICA, AXIS_SHARD
    from elasticsearch_tpu.search.microbatch import (KnnPlaneMicroBatcher,
                                                     PlaneMicroBatcher)
    from elasticsearch_tpu.utils.synth import synthetic_csr_corpus

    mesh = mesh_from_env()
    s_dev = int(mesh.shape[AXIS_SHARD])
    r_dev = int(mesh.shape[AXIS_REPLICA])

    # -- pack: fixed corpus, device-count-independent -----------------------
    rng = np.random.RandomState(1234)
    shards = []
    for si in range(N_SEGMENTS):
        sh = synthetic_csr_corpus(rng, DOCS_PER_SEGMENT, VOCAB, AVG_DL,
                                  zipf_s=1.2)
        sh["term_ids"] = {f"t{t}": t for t in range(VOCAB)}
        shards.append(sh)
    t0 = time.perf_counter()
    plane = DistributedSearchPlane(mesh, shards, field="body")
    pack_ms = (time.perf_counter() - t0) * 1e3
    assert plane._host_csr is None, \
        "host serve must be off: the sweep measures the device path"

    kvecs = [dict(vectors=rng.randn(KNN_DOCS_PER_SEGMENT,
                                    KNN_DIM).astype(np.float32))
             for _ in range(N_SEGMENTS)]
    knn = DistributedKnnPlane(mesh, kvecs, similarity="dot_product")
    assert knn._host_pack is None

    # -- warm the serving lattice (the batcher's own warmup — what the
    # serving cache runs at plane build) -----------------------------------
    batcher = PlaneMicroBatcher(plane)
    t0 = time.perf_counter()
    batcher.warmup(ks=(K,), max_b=N_CLIENTS, sync=True)
    kbatcher = KnnPlaneMicroBatcher(knn)
    kbatcher.warmup(ks=(K,), max_b=N_CLIENTS, sync=True)
    warm_ms = (time.perf_counter() - t0) * 1e3

    # -- parity payload: one fixed eval dispatch per plane kind -------------
    eval_rng = np.random.RandomState(99)
    equeries = _eval_queries(eval_rng, VOCAB)
    vals, hits, totals = plane.search(equeries, k=K, with_totals=True)
    text_results = {
        "vals": [[float(v) for v in row] for row in np.asarray(vals)],
        "hits": [[[int(s), int(d)] for (s, d) in row] for row in hits],
        "totals": [int(t) for t in totals],
    }
    qv = eval_rng.randn(EVAL_B, KNN_DIM).astype(np.float32)
    kvals, khits = knn.search(qv, k=K)
    knn_results = {
        "vals": [[float(v) for v in row] for row in np.asarray(kvals)],
        "hits": [[[int(s), int(d)] for (s, d) in row] for row in khits],
    }

    # -- throughput window: concurrent clients through the micro-batcher ----
    # (one warm round first so every arrival shape the window produces is
    # already compiled; then assert zero steady-state compiles)
    qpool = [[f"t{int(eval_rng.randint(32, VOCAB // 4))}"
              for _ in range(2)] for _ in range(256)]

    def run_window(per: int):
        lat, errs = [], []
        lock = threading.Lock()

        def client(tid):
            try:
                for j in range(per):
                    q = qpool[(tid * per + j) % len(qpool)]
                    t0 = time.perf_counter()
                    batcher.search(q, K)
                    dt = time.perf_counter() - t0
                    with lock:
                        lat.append(dt)
            except BaseException as e:      # noqa: BLE001
                with lock:
                    errs.append(repr(e))
        t0 = time.perf_counter()
        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(N_CLIENTS)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        if errs:
            raise SystemExit(f"serving window errors: {errs[:3]}")
        a = np.asarray(lat)
        return {"qps": round(len(a) / wall, 1),
                "p50_ms": round(float(np.percentile(a, 50) * 1e3), 2),
                "p99_ms": round(float(np.percentile(a, 99) * 1e3), 2),
                "n": int(len(a))}

    run_window(4)                      # warm round (arrival-shape coverage)
    c0 = _compiles_total(tm)
    # best-of-2 steady-state windows: one scheduler hiccup on a shared
    # CPU box must not fail the cross-device-count throughput gate
    w1 = run_window(PER_CLIENT)
    w2 = run_window(PER_CLIENT)
    window = w1 if w1["qps"] >= w2["qps"] else w2
    steady_compiles = _compiles_total(tm) - c0

    # -- PAIRED dispatch-wall ratio vs a 1x1 plane in THIS process ----------
    # Absolute qps drifts +-40% over the minutes a sweep takes (container
    # CPU throttling), swamping any cross-process device-count
    # comparison; a same-process back-to-back measurement of the mesh
    # plane against a fresh 1x1-mesh plane over the SAME corpus and
    # query batch cancels the drift — the ratio is what the sweep's
    # throughput gate judges. Interleaved A/B/A/B reps + median defend
    # against drift WITHIN the paired window too.
    def _dispatch_ms(p, reps=15):
        p.search(equeries, k=K)            # compile + first dispatch
        out = []
        for _ in range(reps):
            t0 = time.perf_counter()
            p.search(equeries, k=K)
            out.append((time.perf_counter() - t0) * 1e3)
        return out

    # (the ref plane's plain make_search_mesh build below does NOT touch
    # the es_mesh_devices gauge — only serving-mesh owners write it)
    mdoc = tm.DEFAULT.metrics_doc()
    mesh_gauge = {s["labels"]["state"]: int(s["value"])
                  for s in mdoc.get("es_mesh_devices",
                                    {}).get("series", [])}
    from elasticsearch_tpu.parallel import make_search_mesh
    ref_plane = DistributedSearchPlane(
        make_search_mesh(n_shards=1, n_replicas=1,
                         devices=jax.devices()[:1]),
        shards, field="body")
    _dispatch_ms(ref_plane, reps=1)        # compile before interleaving
    mesh_ms, ref_ms = [], []
    for _ in range(4):
        mesh_ms += _dispatch_ms(plane, reps=4)
        ref_ms += _dispatch_ms(ref_plane, reps=4)
    mesh_med = float(np.median(mesh_ms))
    ref_med = float(np.median(ref_ms))
    paired = {"mesh_ms_per_batch": round(mesh_med, 2),
              "ref1x1_ms_per_batch": round(ref_med, 2),
              "ratio": round(mesh_med / max(ref_med, 1e-9), 3)}

    # -- per-device resident corpus bytes: measured from live buffers ------
    text_dev_bytes = _measured_device_bytes(
        [plane.docs_dev, plane.impacts_dev, plane.dense_dev])
    kd = knn._device_arrays()
    knn_dev_bytes = _measured_device_bytes(list(kd))

    out = {
        "devices": n_dev,
        "mesh": f"{r_dev}x{s_dev}",
        "mesh_devices": mesh_gauge,
        "pack_ms": round(pack_ms, 1),
        "warmup_ms": round(warm_ms, 1),
        "steady_compiles": int(steady_compiles),
        "serving": window,
        "paired": paired,
        "text": {"results": text_results,
                 "per_device_corpus_bytes": int(text_dev_bytes),
                 "accessor_per_device_bytes":
                     int(plane.device_corpus_bytes()),
                 "docs": int(plane.n_docs_total)},
        "knn": {"results": knn_results,
                "per_device_corpus_bytes": int(knn_dev_bytes),
                "accessor_per_device_bytes":
                    int(knn.device_corpus_bytes()),
                "docs": int(knn.n_docs_total)},
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
