#!/usr/bin/env python
"""Render a ``/_profiler/flamegraph`` doc as collapsed stacks or HTML.

Usage:
    python scripts/flame_dump.py PROFILE.json                # collapsed
    python scripts/flame_dump.py PROFILE.json --html out.html
    python scripts/flame_dump.py --host http://127.0.0.1:9200 \
        [--window both] [--pool dispatcher] [--tenant T] [--html out]
    python scripts/flame_dump.py CAPTURE.json   # a watchdog capture —
                                                # its embedded "profile"
                                                # slice is used

The input is whatever ``GET /_profiler/flamegraph`` returned (single
node or cluster-merged), OR a watchdog capture doc (from
``GET /_flight_recorder/captures/{id}``) whose ``profile`` key embeds
the same row shape. Collapsed output is sorted heaviest-first,
``pool;tenant;shape;frame;... N`` per line — feed it straight to any
flamegraph.pl-compatible tool. ``--html`` writes a SELF-CONTAINED page
(no external JS/CSS): nested proportional-width blocks with hover
titles, one color lane per pool.
"""
from __future__ import annotations

import argparse
import html
import json
import sys
import urllib.parse
import urllib.request

#: stable fill colors per pool lane (anything else hashes into the tail)
_POOL_COLORS = {
    "dispatcher": "#e4573d", "rest": "#4a90d9", "repack": "#e8a33d",
    "warmup": "#8e6bbf", "recovery": "#3db572", "watchdog": "#b05c7a",
    "monitoring": "#6b8f9c", "sampler": "#999999", "main": "#5c6bc0",
}


def load_rows(doc: dict) -> list:
    """Rows from an endpoint doc or a watchdog capture's embedded
    profile slice."""
    if "rows" not in doc and isinstance(doc.get("profile"), dict):
        doc = doc["profile"]
    return list(doc.get("rows") or [])


def collapsed_text(rows: list) -> str:
    from elasticsearch_tpu.common.contprof import collapsed_text as ct
    return ct(rows)


def _flame_tree(rows: list) -> dict:
    from elasticsearch_tpu.common.contprof import flame_json
    return flame_json(rows)


def _render_node(node: dict, total: int, depth: int, out: list) -> None:
    width = 100.0 * node["value"] / max(total, 1)
    if width < 0.1:
        return
    color = _POOL_COLORS.get(node["name"]) if depth == 1 else None
    if color is None:
        color = f"hsl({(hash(node['name']) % 360)}, 45%, 70%)"
    label = html.escape(str(node["name"]))
    out.append(
        f'<div class="fr" style="width:{width:.2f}%">'
        f'<div class="fc" style="background:{color}" '
        f'title="{label} — {node["value"]} samples">{label}</div>')
    kids = node.get("children") or []
    if kids:
        out.append('<div class="fk">')
        for c in kids:
            _render_node(c, node["value"], depth + 1, out)
        out.append("</div>")
    out.append("</div>")


def render_html(rows: list, title: str = "flamegraph") -> str:
    """A self-contained HTML flamegraph: nested blocks sized by sample
    share, rooted at pool -> tenant -> shape -> frames."""
    tree = _flame_tree(rows)
    body: list = []
    for c in tree.get("children") or []:
        _render_node(c, tree["value"], 1, body)
    return ("<!DOCTYPE html><html><head><meta charset='utf-8'>"
            f"<title>{html.escape(title)}</title><style>"
            "body{font:12px monospace;margin:8px}"
            ".fr{display:inline-block;vertical-align:top;"
            "box-sizing:border-box}"
            ".fc{overflow:hidden;white-space:nowrap;border:1px solid "
            "#fff;padding:1px 2px;box-sizing:border-box}"
            ".fk{width:100%}"
            "</style></head><body>"
            f"<h3>{html.escape(title)} — {tree['value']} samples</h3>"
            f"<div style='width:100%'>{''.join(body)}</div>"
            "</body></html>")


def _fetch(host: str, args) -> dict:
    q = {"window": args.window, "limit": str(args.limit)}
    if args.pool:
        q["pool"] = args.pool
    if args.tenant:
        q["tenant"] = args.tenant
    url = (host.rstrip("/") + "/_profiler/flamegraph?" +
           urllib.parse.urlencode(q))
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", nargs="?", help="profile/capture JSON file")
    ap.add_argument("--host", help="fetch live from a node instead")
    ap.add_argument("--window", default="both")
    ap.add_argument("--pool")
    ap.add_argument("--tenant")
    ap.add_argument("--limit", type=int, default=256)
    ap.add_argument("--html", help="write a self-contained HTML "
                                   "flamegraph here")
    args = ap.parse_args(argv)
    if args.host:
        doc = _fetch(args.host, args)
    elif args.path:
        with open(args.path) as f:
            doc = json.load(f)
    else:
        ap.error("need a JSON file or --host")
        return 2
    rows = load_rows(doc)
    if args.pool:
        rows = [r for r in rows if r.get("pool") == args.pool]
    if args.tenant:
        rows = [r for r in rows if r.get("tenant") == args.tenant]
    if args.html:
        with open(args.html, "w") as f:
            f.write(render_html(rows, title=args.html))
        print(f"wrote {args.html} ({len(rows)} rows)")
    else:
        sys.stdout.write(collapsed_text(rows))
    dom = (doc.get("profile") or doc).get("dominant") \
        if isinstance(doc, dict) else None
    if dom:
        print(f"# dominant: pool={dom['pool']} tenant={dom['tenant']} "
              f"shape={dom['shape']} samples={dom['samples']}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
