#!/usr/bin/env python
"""Seeded kill-and-rejoin chaos bench: failover under live traffic +
the paired warm-handoff vs segment-re-pack time-to-warm comparison.

Topology (one process, real TCP loopback cluster):

- 3 nodes; the elected master is left alone (quorum survives every
  kill), the ``chaos`` index (2 shards, 1 replica) is PINNED onto the
  front + victim via the include._id allocation filter so the kill is
  deterministic, not allocator luck.
- A seeded :class:`FaultInjector` adds drop/delay noise on every edge
  during the failover phase — the copy-failover retry machinery runs
  under realistic weather, not a clean network.

Phases:

1. **build** — bulk-index ``BENCH_CHAOS_N_DOCS`` docs, refresh, flush
   (both copies persist identical segments: replication is synchronous
   and the refresh broadcast cuts the same segment on every copy).
2. **failover** — search clients run against the front; the victim is
   killed mid-traffic. Gate: ZERO failed searches after the routing
   settles (the victim stripped). Reported: interactive p99 over the
   recovery window (kill → settle + 5 s), gated by bench_diff.
3. **rejoin (warm)** — the victim restarts on its persisted store;
   recovery re-attaches it and the warm plane handoff imports the
   donor's packed tensors. time_to_warm = first plane-served search
   on the rejoined node, measured from recovery-settled.
4. **rejoin (repack)** — same kill/rejoin with ES_TPU_PLANE_HANDOFF=0:
   the first search pays the synchronous cold pack — the rebuild-storm
   baseline. Gate (in-bench): time_to_repack / time_to_warm >=
   BENCH_CHAOS_MIN_RATIO (default 5).

Prints one JSON doc on stdout (last line), bench_diff-compatible
(``configs`` with a p99-gated throughput entry + the time_to_warm
fields bench_diff gates on growth).

Usage:  python scripts/bench_chaos.py [--out CHAOS_rNN.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# SLO watchdog knobs, bench-scale: production windows (1m/10m) would
# outlast the whole bench, so the kill is judged over 2s/8s windows
# with a 100ms tick — the watchdog must go red INSIDE the failure
# window and clear after the cluster heals (both gated below). Set
# before the package imports so the process engine resolves them.
os.environ.setdefault("ES_TPU_SLO_FAST_S", "2")
os.environ.setdefault("ES_TPU_SLO_SLOW_S", "8")
os.environ.setdefault("ES_TPU_SLO_BURN_RED", "2")
os.environ.setdefault("ES_TPU_SLO_FAILURE_BUDGET", "0.005")
os.environ.setdefault("ES_TPU_SLO_LATENCY_MS", "2000")
os.environ.setdefault("ES_TPU_WATCHDOG_TICK_S", "0.1")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SEED = int(os.environ.get("BENCH_CHAOS_SEED", 42))
N_DOCS = int(os.environ.get("BENCH_CHAOS_N_DOCS", 6000))
N_CLIENTS = int(os.environ.get("BENCH_CHAOS_CLIENTS", 4))
MIN_RATIO = float(os.environ.get("BENCH_CHAOS_MIN_RATIO", 5.0))
BASE_PORT = int(os.environ.get("BENCH_CHAOS_PORT", 29300))
#: realistic lexical shape: a 2000-term Zipf vocabulary (tiny word
#: lists make every pack trivially cheap and the time-to-warm
#: comparison meaningless) + a dense_vector field so the donor's kNN
#: plane (IVF tier: k-means + quantized codes) rides the handoff too
VOCAB_N = int(os.environ.get("BENCH_CHAOS_VOCAB", 2000))
VEC_DIM = int(os.environ.get("BENCH_CHAOS_VEC_DIM", 64))
#: corpus threshold at which the packs build their block-max/IVF tiers
#: (production defaults need 128k+ docs; the bench corpus is smaller,
#: so the knobs come down — the tier build IS the production pack cost
#: the warm handoff exists to skip)
TIER_MIN_DOCS = int(os.environ.get("BENCH_CHAOS_TIER_MIN_DOCS", 4096))


def log(msg):
    print(f"[chaos] {msg}", file=sys.stderr, flush=True)


def wait_for(pred, timeout, msg):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise SystemExit(f"FAIL: timeout waiting for {msg}")


def percentile(vals, q):
    if not vals:
        return 0.0
    vals = sorted(vals)
    i = min(int(len(vals) * q), len(vals) - 1)
    return vals[i]


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="also write the JSON doc to this path")
    args = ap.parse_args(argv)

    import numpy as np
    from elasticsearch_tpu.node.cluster_node import ClusterNode
    from elasticsearch_tpu.transport.tcp import FaultInjector

    tmp = tempfile.mkdtemp(prefix="bench_chaos_")
    peers = {f"n{i}": ("127.0.0.1", BASE_PORT + i) for i in range(3)}
    nodes = {nid: ClusterNode(nid, "127.0.0.1", port, peers,
                              os.path.join(tmp, nid), seed=i)
             for i, (nid, (_h, port)) in enumerate(peers.items())}
    injector = FaultInjector(seed=SEED, drop_rate=0.01, delay_rate=0.05,
                             delay_ms=(1.0, 15.0))

    def install_injector():
        for n in nodes.values():
            n.transport.fault_injector = injector

    t_bench0 = time.monotonic()
    try:
        # -- elect + pick roles -------------------------------------------
        leader = None
        deadline = time.monotonic() + 20.0
        while leader is None and time.monotonic() < deadline:
            ls = [n for n in nodes.values()
                  if n.coordinator.mode == "LEADER"]
            if len(ls) == 1:
                leader = ls[0]
            time.sleep(0.05)
        if leader is None:
            raise SystemExit("FAIL: no leader elected")
        data_ids = sorted(set(nodes) - {leader.node_id})
        front, victim_id = nodes[data_ids[0]], data_ids[1]
        log(f"leader={leader.node_id} front={front.node_id} "
            f"victim={victim_id} (roles re-checked after allocation)")

        # -- build ---------------------------------------------------------
        body = json.dumps({
            "settings": {
                "number_of_shards": 2, "number_of_replicas": 1,
                "index.routing.allocation.include._id":
                    f"{front.node_id},{victim_id}"},
            "mappings": {"properties": {
                "body": {"type": "text"}, "n": {"type": "integer"},
                "vec": {"type": "dense_vector", "dims": VEC_DIM}}},
        }).encode()
        status, _ct, out = front.rest._meta_op("PUT", "/chaos", "", body)
        if status >= 300:
            raise SystemExit(f"FAIL: index create {out[:200]!r}")
        # the cold pack must include the production tiers (block-max +
        # IVF) at this corpus size — on every node that may pack
        front.rest.indices.indices["chaos"].plane_cache \
            .lex_prune_min_docs = TIER_MIN_DOCS
        front.rest.indices.indices["chaos"].plane_cache \
            .knn_ivf_min_docs = TIER_MIN_DOCS

        def in_sync():
            st = front.applied_state
            t = (st.data.get("routing", {}) or {}).get("chaos") or {}
            return t and all(
                e.get("replicas") and
                set(e.get("in_sync") or ()) >= set(e["replicas"])
                for e in t.values())
        wait_for(in_sync, 30.0, "replicas in sync")

        # role re-check: the VICTIM must own at least one primary, so
        # the kill forces a real routing-table promotion (the
        # shard_failover journal event + es_shard_failovers_total the
        # reconstruction gate reads); the survivor is the front/donor
        table0 = (front.applied_state.data.get("routing", {})
                  or {}).get("chaos") or {}
        prim_count = {n: sum(1 for e in table0.values()
                             if e.get("primary") == n)
                      for n in data_ids}
        if prim_count.get(victim_id, 0) == 0:
            front, victim_id = nodes[victim_id], front.node_id
            front.rest.indices.indices["chaos"].plane_cache \
                .lex_prune_min_docs = TIER_MIN_DOCS
            front.rest.indices.indices["chaos"].plane_cache \
                .knn_ivf_min_docs = TIER_MIN_DOCS
        log(f"roles: front={front.node_id} victim={victim_id} "
            f"primaries={prim_count}")

        rng = np.random.RandomState(SEED)
        vocab = [f"w{i}" for i in range(VOCAB_N)]
        zipf = np.clip(rng.zipf(1.1, (N_DOCS + 1000) * 16),
                       1, VOCAB_N) - 1
        t0 = time.monotonic()
        for lo in range(0, N_DOCS, 500):
            lines = []
            for i in range(lo, min(lo + 500, N_DOCS)):
                words = [vocab[zipf[(i * 16 + j) % zipf.size]]
                         for j in range(16)]
                lines.append(json.dumps(
                    {"index": {"_index": "chaos", "_id": f"d{i}"}}))
                lines.append(json.dumps({
                    "body": " ".join(words), "n": i,
                    "vec": [round(float(x), 4) for x in
                            rng.randn(VEC_DIM)]}))
            status, _ct, out = front.rest.handle(
                "POST", "/_bulk", "", ("\n".join(lines) + "\n").encode())
            if status >= 300:
                raise SystemExit(f"FAIL: bulk {out[:200]!r}")
        front.refresh("chaos")
        front.rest.handle("POST", "/chaos/_flush", "", b"")
        log(f"indexed {N_DOCS} docs in "
            f"{time.monotonic() - t0:.1f}s; flushed")

        # prime the donor's POOLED serving generations over the
        # pre-kill base (the bundles the handoff will ship): text via
        # the bag-of-terms plane, kNN via the IVF plane — both through
        # the service's real plane providers
        fsvc = front.rest.indices.indices["chaos"]
        fsvc.searcher().search(
            {"query": {"match": {"body": "w1"}}, "size": 10})
        fsvc.searcher().search(
            {"knn": {"field": "vec", "query_vector": [0.1] * VEC_DIM,
                     "k": 10, "num_candidates": 50}})
        rb0 = fsvc.plane_cache.rebuild_stats()
        if rb0.get("cold", 0) < 2:
            raise SystemExit(f"FAIL: donor generations missing: {rb0}")
        log(f"donor plane generations primed: {rb0}")

        # -- failover under live traffic ----------------------------------
        install_injector()
        reqlog = []           # (t, ok, latency_ms)
        reqlock = threading.Lock()
        stop_flag = threading.Event()
        qbody = json.dumps({"query": {"match": {"body": "w1"}},
                            "size": 10}).encode()

        def client():
            while not stop_flag.is_set():
                t1 = time.monotonic()
                try:
                    st, _c, o = front.rest.handle(
                        "POST", "/chaos/_search", "request_cache=false",
                        qbody)
                    doc = json.loads(o)
                    ok = st == 200 and \
                        doc.get("_shards", {}).get("failed", 0) == 0 \
                        and doc.get("hits", {}).get("hits") is not None
                except Exception:   # noqa: BLE001
                    ok = False
                with reqlock:
                    reqlog.append(
                        (t1, ok, (time.monotonic() - t1) * 1e3))
                time.sleep(0.01)

        def witness_client():
            # journal witness: searches coordinated by the LEADER (which
            # holds no chaos copies) must fan out over the wire, so the
            # kill exercises the real copy-failover wave machinery the
            # flight recorder journals and the SLO watchdog burns on.
            # Unmeasured: the front-coordinated clients above stay
            # apples-to-apples with CHAOS_r01.
            while not stop_flag.is_set():
                try:
                    leader.rest.handle(
                        "POST", "/chaos/_search", "request_cache=false",
                        qbody)
                except Exception:   # noqa: BLE001 — witness traffic
                    pass            # tolerates the weather it records
                time.sleep(0.01)

        wlog = {"ok": 0, "fail": 0}
        wstop = threading.Event()

        def writer():
            i = N_DOCS
            while not wstop.is_set():
                try:
                    front.index_doc("chaos", f"d{i}", {
                        "body": " ".join(
                            vocab[zipf[(i * 16 + j) % zipf.size]]
                            for j in range(16)),
                        "n": i,
                        "vec": [0.01 * (i % 97)] * VEC_DIM})
                    wlog["ok"] += 1
                except Exception:   # noqa: BLE001 — a write hitting the
                    wlog["fail"] += 1   # dead primary pre-failover
                i += 1
                time.sleep(0.02)

        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(N_CLIENTS)]
        threads += [threading.Thread(target=witness_client, daemon=True)
                    for _ in range(2)]
        wthread = threading.Thread(target=writer, daemon=True)
        for t in threads:
            t.start()
        wthread.start()
        time.sleep(2.0)
        t_kill = time.monotonic()
        t_kill_wall = time.time() * 1e3
        nodes[victim_id].stop()
        log("victim killed under live search + index traffic")

        def victim_stripped():
            st = front.applied_state
            t = (st.data.get("routing", {}) or {}).get("chaos") or {}
            return t and all(
                e["primary"] == front.node_id and
                victim_id not in e.get("replicas", ()) and
                victim_id not in (e.get("in_sync") or ())
                for e in t.values())
        wait_for(victim_stripped, 30.0, "failover routing")
        t_settle = time.monotonic()
        t_settle_wall = time.time() * 1e3
        time.sleep(5.0)       # post-settle window (plane builds here)
        fail_window_end_wall = time.time() * 1e3
        injector.heal()
        # clean-traffic drain: the watchdog's slow window must roll the
        # kill's failure burn off so the red state CLEARS — the journal
        # gate below asserts the cleared transition is recorded
        from elasticsearch_tpu.common import flightrec as _fr
        wd = _fr.get_watchdog()
        if wd is None:
            raise SystemExit("FAIL: SLO watchdog is not running")
        wait_for(lambda: wd.status_doc()["status"] == "green", 30.0,
                 "watchdog clear after heal")
        stop_flag.set()
        wstop.set()
        for t in threads:
            t.join(timeout=30.0)
        wthread.join(timeout=30.0)
        front.refresh("chaos")
        log(f"live writes during failover: ok={wlog['ok']} "
            f"failed={wlog['fail']}")

        with reqlock:
            entries = list(reqlog)
        after = [(ok, ms) for (ts, ok, ms) in entries
                 if ts > t_settle + 0.2]
        during = [(ok, ms) for (ts, ok, ms) in entries
                  if t_kill <= ts <= t_settle + 5.0]
        failures_after = sum(1 for ok, _ in after if not ok)
        settle_s = t_settle - t_kill
        recovery_p99 = percentile([ms for _ok, ms in during], 0.99)
        window_qps = len(during) / max(
            (min(t_settle + 5.0, entries[-1][0]) - t_kill), 1e-9) \
            if during else 0.0
        log(f"failover: settle={settle_s:.2f}s "
            f"failures_after_settle={failures_after} "
            f"recovery_p99={recovery_p99:.1f}ms "
            f"window_qps={window_qps:.1f} "
            f"faults={injector.stats()}")
        if failures_after:
            raise SystemExit(
                f"FAIL: {failures_after} client-visible search failures "
                f"AFTER failover settled")


        # -- rejoin legs ---------------------------------------------------
        def rejoin_and_measure(handoff: bool, seed: int):
            """Restart the victim; returns (recovery_s from ctor,
            serve_warm_s from recovery-settled to first plane-served
            search, node). The serving-warm window is the metric: both
            legs pay identical metadata/ops recovery first."""
            if not handoff:
                os.environ["ES_TPU_PLANE_HANDOFF"] = "0"
            try:
                t_re = time.monotonic()
                reborn = ClusterNode(
                    victim_id, "127.0.0.1", peers[victim_id][1], peers,
                    os.path.join(tmp, victim_id), seed=seed)
            finally:
                os.environ.pop("ES_TPU_PLANE_HANDOFF", None)
            nodes[victim_id] = reborn

            def recovered():
                svc = reborn.rest.indices.indices.get("chaos")
                if svc is None or not any(
                        e.searchable_segments() for e in svc.shards):
                    return False
                st = front.applied_state
                t = (st.data.get("routing", {}) or {}).get("chaos") or {}
                return t and all(
                    victim_id in (e.get("in_sync") or ())
                    for e in t.values())
            wait_for(recovered, 60.0, "rejoin recovery")
            recovery_s = time.monotonic() - t_re
            svc = reborn.rest.indices.indices["chaos"]
            svc.plane_cache.lex_prune_min_docs = TIER_MIN_DOCS
            svc.plane_cache.knn_ivf_min_docs = TIER_MIN_DOCS
            # TIMED WINDOW: recovery-settled -> serving planes READY
            # for the node's current pooled view. On the warm leg that
            # is any handoff residue (the transfer/import overlap
            # recovery) + O(delta) resolution; on the repack leg it is
            # the synchronous cold packs (CSR sort-merge tables, dense
            # tier, block-max lexsort, IVF k-means + quantize) — the
            # exact work the first search would stall on. Measuring
            # plane-readiness (not first-search wall) keeps unrelated
            # process-wide XLA mask compiles (jnp.full per novel
            # segment length — paid once per shape, order-biased
            # between the legs) out of the paired comparison.
            pooled = [sg for e in svc.shards
                      for sg in e.searchable_segments()]
            t_w = time.monotonic()
            if handoff:
                deadline = time.monotonic() + 30.0
                while svc.plane_cache.rebuild_stats() \
                        .get("handoff", 0) < 2:
                    if time.monotonic() > deadline:
                        raise SystemExit(
                            "FAIL: warm handoff import incomplete: "
                            f"{svc.plane_cache.rebuild_stats()}")
                    time.sleep(0.005)
            tgen = svc.plane_cache.plane_for(pooled, svc.mapper, "body")
            kgen = svc.plane_cache.knn_plane_for(pooled, svc.mapper,
                                                 "vec")
            serve_warm_s = time.monotonic() - t_w
            if tgen is None or kgen is None:
                raise SystemExit("FAIL: serving planes unavailable "
                                 "after rejoin")
            # untimed verification: real plane-served searches answer
            # through the providers (and the batcher) on the rejoined
            # node
            r = svc.searcher().search(
                {"query": {"match": {"body": "w1"}}, "size": 10})
            rk = svc.searcher().search(
                {"knn": {"field": "vec",
                         "query_vector": [0.1] * VEC_DIM, "k": 10,
                         "num_candidates": 50}})
            assert r.hits and rk.hits, "probe searches returned nothing"
            log(f"rejoin segs={[(sg.seg_id, sg.n_docs) for sg in pooled]}"
                f" planes_ready={serve_warm_s:.3f}s")
            return recovery_s, serve_warm_s, reborn

        # warm leg
        rec_w, warm_s, reborn = rejoin_and_measure(True, seed=11)
        rb_w = reborn.rest.indices.indices["chaos"] \
            .plane_cache.rebuild_stats()
        if rb_w.get("handoff", 0) < 2 or rb_w.get("cold", 0) != 0:
            raise SystemExit(f"FAIL: warm leg did not serve from the "
                             f"handoff import: {rb_w}")
        log(f"warm leg: recovery={rec_w:.2f}s planes_ready={warm_s:.3f}s "
            f"{rb_w}")

        # repack leg: kill again, rejoin with the handoff disabled
        reborn.stop()
        wait_for(victim_stripped, 30.0, "second failover")
        rec_r, repack_s, reborn2 = rejoin_and_measure(False, seed=12)
        rb_r = reborn2.rest.indices.indices["chaos"] \
            .plane_cache.rebuild_stats()
        if rb_r.get("cold", 0) < 2 or rb_r.get("handoff", 0) != 0:
            raise SystemExit(f"FAIL: repack leg did not cold-pack: "
                             f"{rb_r}")
        log(f"repack leg: recovery={rec_r:.2f}s "
            f"planes_ready={repack_s:.3f}s {rb_r}")

        ratio = repack_s / max(warm_s, 1e-4)
        if ratio < MIN_RATIO:
            raise SystemExit(
                f"FAIL: warm handoff only {ratio:.1f}x faster than the "
                f"segment re-pack path (gate {MIN_RATIO}x): "
                f"warm={warm_s:.3f}s repack={repack_s:.3f}s")

        # -- journal reconstruction -----------------------------------
        # The closing gate: the kill must be reconstructable END TO END
        # from the flight-recorder journal alone — failover waves and
        # the master's promotion inside the failure window, the
        # watchdog's red transition + automatic capture inside that
        # window, the cleared transition after the heal, and the warm
        # handoff (manifest -> chunks -> done) after that, in order.
        st, _c, jout = front.rest.handle(
            "GET", "/_flight_recorder", "limit=4000", b"")
        if st != 200:
            raise SystemExit(f"FAIL: GET /_flight_recorder -> {st}")
        jdoc = json.loads(jout)
        events = jdoc["events"]

        def sel(tname, lo=None, hi=None):
            return [e for e in events if e["type"] == tname
                    and (lo is None or e["ts_ms"] >= lo)
                    and (hi is None or e["ts_ms"] <= hi)]

        from collections import Counter
        log(f"journal: {len(events)} events "
            f"{dict(Counter(e['type'] for e in events))} "
            f"window=[{t_kill_wall:.0f},{fail_window_end_wall:.0f}] "
            f"span=[{events[0]['ts_ms']:.0f},{events[-1]['ts_ms']:.0f}]"
            if events else "journal: EMPTY")
        fw = sel("failover_wave", t_kill_wall, fail_window_end_wall)
        sf = sel("shard_failover", t_kill_wall, fail_window_end_wall)
        wdog = sel("watchdog", t_kill_wall, None)
        red = [e for e in wdog
               if e["ts_ms"] <= fail_window_end_wall and
               str((e.get("attrs") or {}).get("transition", ""))
               .endswith("->red")]
        caps = sel("capture", t_kill_wall, fail_window_end_wall)
        caps = [e for e in caps
                if (e.get("attrs") or {}).get("trigger") == "slo_red"]
        if not fw:
            raise SystemExit("FAIL: journal holds no failover_wave "
                             "events inside the failure window")
        if not sf:
            raise SystemExit("FAIL: journal holds no shard_failover "
                             "(promotion) event inside the failure "
                             "window")
        if not red or not caps:
            raise SystemExit(
                f"FAIL: watchdog did not go red + capture inside the "
                f"failure window (red={len(red)} captures={len(caps)}; "
                f"watchdog events: "
                f"{[(e.get('attrs') or {}).get('transition') for e in wdog]})")
        cap_ts = caps[0]["ts_ms"]
        cleared = [e for e in wdog if e["ts_ms"] > cap_ts and
                   str((e.get("attrs") or {}).get("transition", ""))
                   .startswith("red->")]
        if not cleared:
            raise SystemExit("FAIL: journal holds no red-> cleared "
                             "watchdog transition after the capture")
        cleared_ts = cleared[0]["ts_ms"]
        hand = {t: sel(t, cleared_ts) for t in
                ("handoff_manifest", "handoff_chunk", "handoff_done")}
        if not all(hand.values()):
            raise SystemExit(
                f"FAIL: warm-handoff events missing after the clear: "
                f"{ {t: len(v) for t, v in hand.items()} }")
        # in-order: waves -> capture -> cleared -> handoff
        order = (min(e["ts_ms"] for e in fw), cap_ts, cleared_ts,
                 min(e["ts_ms"] for e in hand["handoff_manifest"]))
        if list(order) != sorted(order):
            raise SystemExit(f"FAIL: journal event order broken: "
                             f"{order}")
        journal_cfg = {
            "failover_wave_events": len(fw),
            "shard_failover_events": len(sf),
            "handoff_manifest_events": len(hand["handoff_manifest"]),
            "handoff_chunk_events": len(hand["handoff_chunk"]),
            "handoff_done_events": len(hand["handoff_done"]),
            "capture_in_window": True,
            "watchdog_cleared": True,
            "capture_lag_ms": round(cap_ts - t_kill_wall, 1),
            "journal": jdoc.get("journal"),
        }
        log(f"journal reconstruction OK: {journal_cfg}")

        from elasticsearch_tpu.common import telemetry as _tm
        snap = _tm.DEFAULT.metrics_doc()
        rec_bytes = {s["labels"]["kind"]: int(s["value"]) for s in
                     snap.get("es_recovery_bytes_total",
                              {}).get("series", ())}
        doc = {
            "metric": "chaos kill-and-rejoin (failover + warm handoff)",
            "backend": "cpu", "chaos": True, "seed": SEED,
            "n_docs": N_DOCS,
            "wall_s": round(time.monotonic() - t_bench0, 1),
            "recovery_bytes": rec_bytes,
            "configs": {
                "chaos_failover": {
                    "value": round(window_qps, 1), "unit": "queries/s",
                    "p99_ms": round(recovery_p99, 1), "p99_gate": True,
                    "failures_after_settle": failures_after,
                    "settle_s": round(settle_s, 2),
                    "clients": N_CLIENTS,
                    "faults": injector.stats()},
                "chaos_rejoin_warm": {
                    "value": round(ratio, 1), "unit": "x",
                    "time_to_warm_s": round(warm_s, 3),
                    "time_to_repack_s": round(repack_s, 3),
                    "recovery_warm_s": round(rec_w, 2),
                    "recovery_repack_s": round(rec_r, 2),
                    "min_ratio_gate": MIN_RATIO},
                "chaos_journal": journal_cfg,
            },
        }
        line = json.dumps(doc)
        if args.out:
            with open(args.out, "w") as f:
                f.write(line + "\n")
        print(line)
        return 0
    finally:
        for n in list(nodes.values()):
            try:
                if not n.stopped:
                    n.stop()
            except Exception:   # noqa: BLE001
                pass


if __name__ == "__main__":
    sys.exit(main())
