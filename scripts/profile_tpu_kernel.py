"""Per-stage profile of the tiered BM25 dispatch on the real TPU.

Times each kernel stage in isolation at the headline bench shapes
(B=64, Q=4, L=131072, n_pad=2^23, T=256, C=2^19) to find where the
3.7 s/dispatch goes: tunnel RTT, H2D transfer, sparse sort, candidate
gather, dense scan, or the final merges.  Run on the tunneled chip:

    python scripts/profile_tpu_kernel.py [--small]
"""
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax                                               # noqa: E402

if "--cpu" in sys.argv:
    # env alone does not win against the ambient sitecustomize backend
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp                                  # noqa: E402
from jax import lax                                      # noqa: E402


def timeit(label, fn, *args, iters=5, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
        jax.tree_util.tree_map(
            lambda x: x.block_until_ready()
            if hasattr(x, "block_until_ready") else x, out)
    ts = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.tree_util.tree_map(
            lambda x: x.block_until_ready()
            if hasattr(x, "block_until_ready") else x, out)
        ts.append(time.perf_counter() - t0)
    med = float(np.median(ts)) * 1e3
    print(f"{label:<42s} {med:9.1f} ms  (min {min(ts)*1e3:.1f})")
    return med


def main():
    small = "--small" in sys.argv
    print(f"devices: {jax.devices()}")
    B, Q, K = 64, 4, 10
    if small:
        n_pad, L, T, C = 1 << 18, 1 << 12, 64, 1 << 15
    else:
        n_pad, L, T, C = 1 << 23, 1 << 17, 256, 1 << 19
    n_blk = n_pad // C
    n_postings = 10 * n_pad
    rng = np.random.RandomState(0)

    # -- 0. dispatch overhead -------------------------------------------
    one = jnp.ones((8,), jnp.float32)
    f_null = jax.jit(lambda x: x + 1)
    timeit("null jit dispatch (RTT floor)", f_null, one)

    for size, lbl in ((1 << 10, "1KB"), (1 << 20, "1MB"),
                      (1 << 24, "16MB")):
        host = np.zeros(size // 4, np.float32)
        timeit(f"device_put {lbl}", jax.device_put, host)

    # -- stage inputs ---------------------------------------------------
    postings_docs = jnp.asarray(
        np.sort(rng.randint(0, n_pad, n_postings)).astype(np.int32))
    postings_imp = jnp.asarray(
        rng.rand(n_postings).astype(np.float32))
    starts = jnp.asarray(rng.randint(
        0, n_postings - L, (B, Q)).astype(np.int32))
    lengths = jnp.asarray(np.full((B, Q), L, np.int32))
    idfw = jnp.asarray(rng.rand(B, Q).astype(np.float32))
    W = jnp.asarray(rng.rand(B, T).astype(np.float32))
    blocks_host = np.zeros((n_blk, T, C), dtype=np.float32)
    for b in range(n_blk):
        blk = rng.rand(T, C).astype(np.float32)
        blk *= (rng.rand(T, C) < 0.02)
        blocks_host[b] = blk
    dense_blocks = jnp.asarray(blocks_host).astype(jnp.bfloat16)
    del blocks_host
    print(f"shapes: n_pad={n_pad} L={L} T={T} C={C} n_blk={n_blk} "
          f"dense={dense_blocks.nbytes/2**30:.2f}GiB")

    from elasticsearch_tpu.ops.sorted_merge import bm25_merge_candidates
    from elasticsearch_tpu.ops.tiered_bm25 import (
        dense_stream_topk, gather_dense_for_candidates,
        merge_topk_lists, tiered_bm25_topk)

    # -- 1. sparse sorted-merge alone -----------------------------------
    @jax.jit
    def sparse_only(pd, pi, st, ln, iw):
        def per_q(s, l, w):
            return bm25_merge_candidates(pd, pi, s, l, w,
                                         n_pad=n_pad, L=L)
        return jax.vmap(per_q)(st, ln, iw)

    timeit(f"sparse merge (sort {B}x{Q}x{L})", sparse_only,
           postings_docs, postings_imp, starts, lengths, idfw)

    # -- 2. dense scan alone --------------------------------------------
    @jax.jit
    def dense_only(w, blocks):
        return dense_stream_topk(w, blocks, k=K)

    timeit(f"dense scan ({n_blk} blk matmul+top_k)", dense_only,
           W, dense_blocks)

    # -- 2b. dense as ONE matmul + ONE topk (alternative) ---------------
    flat = dense_blocks.transpose(1, 0, 2).reshape(T, n_pad)

    @jax.jit
    def dense_flat(w, fb):
        s = lax.dot_general(w, fb.astype(jnp.float32),
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
        s = jnp.where(s > 0, s, -jnp.inf)
        return lax.top_k(s, K)

    try:
        timeit("dense ONE matmul+topk (2.1GiB scores)", dense_flat,
               W, flat)
    except Exception as e:
        print(f"dense flat variant failed: {e}")

    # -- 3. candidate dense-gather alone --------------------------------
    cand = jnp.asarray(rng.randint(
        0, n_pad, (B, Q * L)).astype(np.int32))
    rid = jnp.asarray(rng.randint(0, T, (B, Q)).astype(np.int32))
    dw = jnp.asarray(rng.rand(B, Q).astype(np.float32))

    @jax.jit
    def gather_only(blocks, cd, r, w):
        def per_q(c, rr, ww):
            return gather_dense_for_candidates(blocks, c, rr, ww,
                                               n_pad=n_pad)
        return jax.vmap(per_q)(cd, rid, dw)

    timeit(f"candidate dense gather ({B}x{Q*L})", gather_only,
           dense_blocks, cand, rid, dw)

    # -- 4. full tiered kernel ------------------------------------------
    dense_rid = rid
    dense_w = dw

    @jax.jit
    def full(pd, pi, blocks, st, ln, iw, r, w2, w3):
        return tiered_bm25_topk(pd, pi, blocks, st, ln, iw, r, w2, w3,
                                n_pad=n_pad, L=L, k=K)

    timeit("FULL tiered kernel", full, postings_docs, postings_imp,
           dense_blocks, starts, lengths, idfw, dense_rid, dense_w, W)

    # same kernel, per-dispatch args passed as HOST numpy (what the
    # serving path does each request) — the delta is transfer overhead
    h_starts = np.asarray(starts)
    h_lengths = np.asarray(lengths)
    h_idfw = np.asarray(idfw)
    h_rid = np.asarray(dense_rid)
    h_dw = np.asarray(dense_w)
    h_W = np.asarray(W)
    timeit("FULL kernel, host-numpy query args", full,
           postings_docs, postings_imp, dense_blocks,
           h_starts, h_lengths, h_idfw, h_rid, h_dw, h_W)

    # -- 5. L sensitivity ------------------------------------------------
    for L2 in (1 << 12, 1 << 14, 1 << 15):
        st2 = jnp.asarray(rng.randint(
            0, n_postings - L2, (B, Q)).astype(np.int32))
        ln2 = jnp.asarray(np.full((B, Q), L2, np.int32))

        @jax.jit
        def sparse_L2(pd, pi, st, ln, iw, L2=L2):
            def per_q(s, l, w):
                return bm25_merge_candidates(pd, pi, s, l, w,
                                             n_pad=n_pad, L=L2)
            return jax.vmap(per_q)(st, ln, iw)

        timeit(f"sparse merge at L={L2}", sparse_L2,
               postings_docs, postings_imp, st2, ln2, idfw)


if __name__ == "__main__":
    main()
