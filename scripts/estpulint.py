#!/usr/bin/env python
"""estpulint — project-wide static analysis gate.

Three rule families over ``elasticsearch_tpu/`` (see STATIC_ANALYSIS.md
for the full rule catalogue):

- ESTP-J* jit-boundary hygiene (host syncs on the device hot path,
  impure calls inside jit, mutable defaults, unbucketed static shapes);
- ESTP-L* lock-order safety (acquisition-graph cycles, telemetry under
  serving locks) — cross-checked at runtime by the lockdep witness
  (``ES_TPU_LOCKDEP=1``, ``elasticsearch_tpu/common/lockdep.py``);
- ESTP-R*/T* lockset data-race analysis (unguarded multi-thread-root
  state, check-then-act, unjoined thread lifecycle) — cross-checked at
  runtime by the racedep happens-before witness
  (``ES_TPU_RACEDEP=record|raise``, ``elasticsearch_tpu/common/racedep.py``);
- ESTP-C* telemetry-catalogue discipline (registry ↔ TELEMETRY.md ↔
  health-indicator three-way consistency; the old telemetry_lint).

The gate is ZERO NEW FINDINGS: every finding must either be fixed or
appear in the checked-in baseline (``ESTPULINT_BASELINE.json``) with a
one-line justification. Stale baseline entries (fixed findings whose
entry lingers) warn but do not fail.

Usage:
  python scripts/estpulint.py                 # full-package scan, gate
  python scripts/estpulint.py --diff main     # only files changed vs ref
  python scripts/estpulint.py --rules ESTP-L  # one family
  python scripts/estpulint.py --no-runtime    # skip the live-registry
                                              # workload (C01/C02)
  python scripts/estpulint.py --update-baseline   # rewrite the baseline
                                                  # from current findings
  python scripts/estpulint.py --sarif out.sarif   # SARIF 2.1.0 for CI /
                                                  # editor annotation
  python scripts/estpulint.py --no-cache          # bypass the parsed-
                                                  # model cache
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO_ROOT, "ESTPULINT_BASELINE.json")

if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def _changed_files(ref: str):
    out = subprocess.run(
        ["git", "diff", "--name-only", ref, "--"],
        cwd=REPO_ROOT, capture_output=True, text=True, check=True).stdout
    # brand-new files are part of "what changed" for pre-commit purposes
    # but invisible to `git diff REF` until tracked
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard"],
        cwd=REPO_ROOT, capture_output=True, text=True, check=True).stdout
    return {line.strip() for line in (out + untracked).splitlines()
            if line.strip()}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", default=BASELINE,
                    help="baseline file (default ESTPULINT_BASELINE.json)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(existing justifications are preserved)")
    ap.add_argument("--diff", metavar="REF",
                    help="report only findings in files changed vs the "
                         "git ref (the project model is still built "
                         "whole); skips the runtime catalogue workload "
                         "unless telemetry surfaces changed")
    ap.add_argument("--rules", action="append", default=None,
                    metavar="PREFIX",
                    help="rule-id prefix filter (repeatable), e.g. "
                         "ESTP-J or ESTP-L01")
    ap.add_argument("--no-runtime", action="store_true",
                    help="skip the live-registry catalogue workload "
                         "(ESTP-C01/C02); static rules still run")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also list baselined (matched) findings")
    ap.add_argument("--sarif", metavar="PATH",
                    help="also write findings as SARIF 2.1.0 (new "
                         "findings as errors, baselined ones as "
                         "suppressed warnings with their "
                         "justifications)")
    ap.add_argument("--no-cache", action="store_true",
                    help="bypass the parsed-model cache "
                         "(.estpulint_cache/, keyed on file mtimes)")
    args = ap.parse_args(argv)

    from elasticsearch_tpu.devtools import analyzer

    if args.update_baseline and (args.diff or args.rules or
                                 args.no_runtime):
        # a filtered scan sees only a SUBSET of findings; rewriting the
        # baseline from it would silently erase every out-of-scope
        # entry (and its justification)
        print("--update-baseline requires a full unfiltered scan "
              "(drop --diff/--rules/--no-runtime)", file=sys.stderr)
        return 2

    report_files = None
    runtime = not args.no_runtime
    if args.diff:
        changed = _changed_files(args.diff)
        report_files = {p for p in changed if p.endswith(".py")}
        # the runtime workload only gates telemetry surfaces — skip it
        # in diff mode unless one of those (or the catalogue itself)
        # changed; when it does run, its findings anchor to
        # TELEMETRY.md, which must then be in the report set or they
        # would be filtered out unseen
        telem_surfaces = {"elasticsearch_tpu/common/telemetry.py",
                          "elasticsearch_tpu/common/health.py",
                          "elasticsearch_tpu/common/lockdep.py",
                          "elasticsearch_tpu/devtools/rules_catalogue.py",
                          "TELEMETRY.md"}
        if runtime:
            runtime = bool(changed & telem_surfaces)
        if runtime:
            report_files.add("TELEMETRY.md")

    cache = None
    if not args.no_cache:
        from elasticsearch_tpu.devtools import model_cache
        cache = model_cache.default_cache(REPO_ROOT)

    findings = analyzer.scan_project(
        REPO_ROOT, rules=tuple(args.rules) if args.rules else None,
        runtime=runtime, report_files=report_files, cache=cache)

    baseline = analyzer.load_baseline(args.baseline)
    new, matched, stale = analyzer.compare_with_baseline(findings, baseline)

    if args.sarif:
        from elasticsearch_tpu.devtools import sarif
        justs = {(d.get("rule"), d.get("file"), d.get("symbol", ""),
                  d.get("detail", "")): d.get("justification", "")
                 for d in baseline}
        sarif.write_sarif(args.sarif, new, matched, justs)
        print(f"sarif written: {len(new)} new + {len(matched)} "
              f"suppressed -> {args.sarif}")

    if args.update_baseline:
        justs = {(d.get("rule"), d.get("file"), d.get("symbol", ""),
                  d.get("detail", "")): d.get("justification")
                 for d in baseline}
        analyzer.save_baseline(args.baseline, findings, justs)
        print(f"baseline rewritten: {len(findings)} findings -> "
              f"{os.path.relpath(args.baseline, REPO_ROOT)}")
        return 0

    for f in new:
        print(f"NEW {f.render()}")
    if args.verbose:
        for f in matched:
            print(f"baselined {f.render()}")
    if stale and report_files is None and not args.rules:
        # a stale entry only means something when every rule ran over
        # the whole tree — under --diff/--rules the filtered-out
        # entries all look stale
        print(f"note: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} (finding fixed; run "
              f"--update-baseline to drop):", file=sys.stderr)
        for d in stale:
            print(f"  [{d.get('rule')}] {d.get('file')} "
                  f"{d.get('symbol')}: {d.get('detail')}", file=sys.stderr)
    if new:
        print(f"estpulint: {len(new)} NEW finding"
              f"{'' if len(new) == 1 else 's'} "
              f"({len(matched)} baselined). Fix them or justify in "
              f"{os.path.relpath(args.baseline, REPO_ROOT)}.",
              file=sys.stderr)
        return 1
    scope = f"{len(report_files)} changed files" if report_files is not None \
        else "full package"
    print(f"estpulint OK ({scope}): 0 new findings, "
          f"{len(matched)} baselined")
    return 0


if __name__ == "__main__":
    sys.exit(main())
