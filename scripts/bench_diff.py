#!/usr/bin/env python3
"""Diff two ``BENCH_*.json`` (or ``MULTICHIP_*.json``) result files per
config and gate on throughput regressions.

Usage::

    python scripts/bench_diff.py BENCH_r05.json BENCH_r06.json
    python scripts/bench_diff.py old.json new.json --threshold 0.10
    python scripts/bench_diff.py MULTICHIP_r05.json MULTICHIP_r06.json

``MULTICHIP_*.json`` files (the driver's dryrun record: ``{"n_devices",
"rc", "ok", "tail"}`` where ``tail`` holds ``dryrun_multichip``'s sweep
summary line) are detected by shape and their 1/2/4/8-device sweep
points become per-device-count configs (``multichip_4dev`` …): the
usual >threshold throughput gate applies per device count, per-device
packed-corpus bytes must not GROW past the threshold, and the new
file's own sweep must still show ~1/n_shards bytes scaling. Device
counts present on only one side are SKIPPED with a note (a machine
with fewer cores sweeps fewer points — that is not a regression).
Legacy empty-``tail`` shells contribute no configs, so every new point
is one-sided and the diff passes with notes.

Prints one line per comparable metric — the headline plus every entry in
``configs`` that carries a throughput ``value`` (unit ``*/s``) — with the
old/new numbers, the relative delta, and ``p99_ms`` movement where both
sides report it. Exits **1** when any throughput metric regressed by
more than ``--threshold`` (default 10%), OR when a config's
``recall_at_k`` dropped by more than 0.01 absolute (recall is a
correctness budget, not a throughput — it gets its own, tighter gate),
so CI can ratchet on bench trajectories instead of eyeballing the
``BENCH_r*`` files.

Configs present in only one of the two files are SKIPPED with a note
(added / removed), never gated — BENCH files span rounds where configs
appear and (on backend fallbacks) drop out; a pairwise diff can only
judge what both sides measured. Error-shaped configs (``{"error":
...}``) still gate when the other side had a real number — a config
that stopped producing results IS a regression.
"""

from __future__ import annotations

import argparse
import json
import sys


def _is_throughput(doc) -> bool:
    return (isinstance(doc, dict)
            and isinstance(doc.get("value"), (int, float))
            and str(doc.get("unit", "")).endswith("/s"))


def _unwrap(doc: dict) -> dict:
    """Accept both the raw bench line and the driver's wrapper (the
    ``BENCH_r*.json`` files nest the bench JSON under ``parsed``;
    ``MULTICHIP_r*.json`` files carry the sweep summary inside
    ``tail``)."""
    if isinstance(doc.get("parsed"), dict) and (
            "value" in doc["parsed"] or "configs" in doc["parsed"]):
        return doc["parsed"]
    # the MULTICHIP record is keyed by n_devices (BENCH wrappers carry
    # rc/tail TOO, but nest the bench doc under parsed — handled above)
    if "tail" in doc and "n_devices" in doc:
        return _multichip_configs(doc)
    return doc


def _multichip_configs(doc: dict) -> dict:
    """MULTICHIP record -> configs-shaped doc: one throughput config per
    swept device count, carrying the per-device corpus bytes so the diff
    can gate bytes growth and scaling. An empty/unparseable ``tail``
    (the pre-sweep shells) yields zero configs."""
    sweep = None
    for line in reversed(str(doc.get("tail", "")).strip().splitlines()):
        try:
            cand = json.loads(line)
        except (ValueError, TypeError):
            continue
        if isinstance(cand, dict) and isinstance(cand.get("sweep"), list):
            sweep = cand["sweep"]
            break
    configs = {}
    for pt in sweep or []:
        c = int(pt.get("devices", 0))
        configs[f"multichip_{c}dev"] = {
            "value": pt.get("qps"), "unit": "queries/s",
            "p99_ms": pt.get("p99_ms"), "devices": c,
            "mesh": pt.get("mesh"),
            "steady_compiles": pt.get("steady_compiles"),
            "text_device_bytes": pt.get("text_device_bytes"),
            "knn_device_bytes": pt.get("knn_device_bytes"),
        }
    return {"backend": "cpu-virtual", "multichip": True,
            "configs": configs}


def _multichip_scaling_check(new: dict, tol_lo: float = 0.7,
                             tol_hi: float = 1.35):
    """Intra-file gate on the NEW sweep: per-device packed-corpus bytes
    at c devices must sit within [tol_lo, tol_hi] of the 1/n_shards
    ideal extrapolated from the sweep's smallest device count — the
    whole point of sharding the planes. Returns failure strings."""
    cfgs = {c["devices"]: c for c in (new.get("configs") or {}).values()
            if isinstance(c.get("devices"), int)}
    if not cfgs:
        # empty new sweep: a regression ONLY when the old side had one
        # (the caller checks); two legacy shells diff clean with notes
        return []
    base_c = min(cfgs)
    out = []
    for kind in ("text_device_bytes", "knn_device_bytes"):
        b0 = cfgs[base_c].get(kind)
        if not isinstance(b0, (int, float)) or b0 <= 0:
            continue
        for c, cfg in sorted(cfgs.items()):
            got = cfg.get(kind)
            ideal = b0 * base_c / c
            if not isinstance(got, (int, float)) or \
                    not (tol_lo * ideal <= got <= tol_hi * ideal):
                out.append(
                    f"multichip_{c}dev {kind}={got} breaks ~1/n_shards "
                    f"scaling (ideal ~{ideal:.0f} from {base_c}dev)")
    return out


def _metrics(doc: dict):
    """Flatten one bench JSON into {name: config-doc} — the headline
    (top-level value/unit) under ``<metric>``, then every config."""
    out = {}
    if _is_throughput(doc):
        # stable key: the metric string embeds n_docs, which differs
        # across backends/scales and would break the pairing
        out["headline"] = doc
    for name, cfg in (doc.get("configs") or {}).items():
        out[f"configs.{name}"] = cfg if isinstance(cfg, dict) else {}
    return out


#: absolute recall_at_k drop that fails the diff (recall is a
#: correctness budget — 1% absolute is already a visible quality change)
RECALL_DROP_MAX = 0.01

#: relative p99 increase that fails the diff for p99-gated configs
#: (configs carrying ``p99_gate: true`` — lexical_10m_prune opts in:
#: its whole point is a latency profile, so throughput alone can't
#: certify it)
P99_RISE_MAX = 0.25

#: per-device packed-bytes growth that fails a MULTICHIP diff — fixed,
#: never widened with the qps threshold: packed bytes are deterministic
#: (measured from live buffers over a seeded corpus), so any growth is
#: a real packing/sharding change, not noise
DEVICE_BYTES_GROW_MAX = 0.10

#: relative per-kernel roofline-efficiency drop that fails the diff on
#: configs embedding an ``efficiency`` summary (bench.py's per-config
#: roofline audit delta): a dispatch moving its modeled bytes >20%
#: slower than the baseline run on the same machine is a kernel or
#: pipeline regression even when batched throughput masks it. Kernels
#: present on only one side SKIP with a note (config drift, not a
#: regression); windows under the dispatch floor carry too little
#: signal to gate.
EFF_DROP_MAX = 0.20
EFF_MIN_DISPATCHES = 4

#: chaos-config time_to_warm gate: regression only when the new side
#: BOTH grew past this relative threshold AND sits above the absolute
#: noise floor — the warm import usually completes while recovery is
#: still replaying ops, so the measured residue ranges 1-50 ms and a
#: pure relative gate would flake on scheduler noise
TIME_TO_WARM_GROW_MAX = 2.0
TIME_TO_WARM_FLOOR_S = 0.25

#: p99 threshold used instead when BOTH sides are chaos runs: the
#: recovery-window p99 is measured over a fault-injected loopback
#: window of a few hundred requests — run-to-run it swings several x
#: (15-80 ms observed); the gate exists to catch failover STALLS
#: (p99 jumping to seconds), not scheduler noise
CHAOS_P99_RISE_MAX = 3.0

#: throughput threshold used instead when BOTH sides are chaos runs
#: (the multichip precedent): the recovery-window qps covers ~6 s of
#: fault-injected loopback traffic sharing cores with the cluster, the
#: witness clients and the watchdog — consecutive same-code runs
#: measured 245 vs 225 q/s (~8%), so the default 10% gate flakes; the
#: exact chaos gates (zero failures after settle, time_to_warm,
#: p99 stall, journal reconstruction) are unaffected
CHAOS_QPS_DROP_MAX = 0.25

#: tiered_capacity acceptance: the hot-set (Zipf head) p99 under the
#: 10x over-subscribed tier budget must stay within this ratio of the
#: device-resident baseline measured in the SAME run; the absolute
#: floor keeps sub-millisecond jitter from flaking the ratio
HOT_P99_RATIO_MAX = 1.25
HOT_P99_FLOOR_MS = 2.0

#: promotion-count drift between rounds: tier transitions under the
#: seeded Zipf mix are near-deterministic — the count doubling (plus
#: slack) means the hysteresis/anti-thrash policy regressed into a
#: demote/promote loop even if qps held
PROMOTION_DRIFT_FACTOR = 2.0
PROMOTION_DRIFT_SLACK = 10


#: insights overhead acceptance: the serving bench's back-to-back
#: insights-on vs insights-off windows (``configs.rest_serving_32_
#: clients.insights``) must show fingerprinting + heavy-hitter sketches
#: costing <= this much headline throughput. ``pct_off_vs_on`` is
#: (off_qps - on_qps) / on_qps * 100 — positive means insights cost
#: something. One-sided on the FIRST landing (old side has no
#: ``insights`` pair): SKIP with a note, gate from the next diff on.
INSIGHTS_OVERHEAD_MAX_PCT = 2.0

#: continuous-profiler overhead acceptance: the serving bench's ABBA
#: sampler-on vs sampler-off windows (``configs.rest_serving_32_
#: clients.contprof``) must show the always-on flamegraph sampler
#: costing <= this much headline throughput. Same one-sided discipline
#: as the insights gate: SKIP with a note on the FIRST landing (old
#: side has no ``contprof`` pair), gate from the next diff on.
CONTPROF_OVERHEAD_MAX_PCT = 2.0

#: multi-tenant QoS acceptance (``configs.qos_overload.qos``): with
#: admission control on, the interactive tenants' p99 under the abusive
#: flood must stay within this ratio of the same run's unloaded
#: baseline — the enforcement gap the tentpole closes. One-sided on the
#: FIRST landing (old side has no ``qos`` dict): SKIP with a note, gate
#: from the next diff on.
QOS_PROTECTED_P99_RATIO_MAX = 3.0


def _insights_check(old: dict, new: dict):
    """Insights-overhead gate over the NEW side's own paired on/off
    windows; the old side's presence only decides gate-vs-skip (a
    pairwise diff can't judge a measurement the baseline never made).
    Returns (report lines, failure strings)."""
    lines, fails = [], []
    for name, cfg in (new.get("configs") or {}).items():
        ins = cfg.get("insights") if isinstance(cfg, dict) else None
        if not isinstance(ins, dict) or \
                not isinstance(ins.get("pct_off_vs_on"), (int, float)):
            continue
        pct = float(ins["pct_off_vs_on"])
        ocfg = (old.get("configs") or {}).get(name)
        oins = ocfg.get("insights") if isinstance(ocfg, dict) else None
        label = (f"  configs.{name:33s} insights on "
                 f"{ins.get('on_qps')} vs off {ins.get('off_qps')} "
                 f"req/s  overhead {pct:+.2f}%")
        if not isinstance(oins, dict):
            lines.append(label + "  SKIPPED gate (first landing — no "
                                 "insights pair in old)")
            continue
        if pct > INSIGHTS_OVERHEAD_MAX_PCT:
            lines.append(label + "  << INSIGHTS-OVERHEAD REGRESSION")
            fails.append(f"configs.{name} (insights overhead "
                         f"{pct:+.2f}% past "
                         f"{INSIGHTS_OVERHEAD_MAX_PCT:.0f}%)")
        else:
            lines.append(label)
    return lines, fails


def _contprof_check(old: dict, new: dict):
    """Continuous-profiler overhead gate over the NEW side's own paired
    on/off windows; the old side's presence only decides gate-vs-skip
    (the ``_insights_check`` pattern). Returns (report lines, failure
    strings)."""
    lines, fails = [], []
    for name, cfg in (new.get("configs") or {}).items():
        cp = cfg.get("contprof") if isinstance(cfg, dict) else None
        if not isinstance(cp, dict) or \
                not isinstance(cp.get("pct_off_vs_on"), (int, float)):
            continue
        pct = float(cp["pct_off_vs_on"])
        ocfg = (old.get("configs") or {}).get(name)
        ocp = ocfg.get("contprof") if isinstance(ocfg, dict) else None
        label = (f"  configs.{name:33s} contprof on "
                 f"{cp.get('on_qps')} vs off {cp.get('off_qps')} "
                 f"req/s  overhead {pct:+.2f}%")
        if not isinstance(ocp, dict):
            lines.append(label + "  SKIPPED gate (first landing — no "
                                 "contprof pair in old)")
            continue
        if pct > CONTPROF_OVERHEAD_MAX_PCT:
            lines.append(label + "  << CONTPROF-OVERHEAD REGRESSION")
            fails.append(f"configs.{name} (contprof overhead "
                         f"{pct:+.2f}% past "
                         f"{CONTPROF_OVERHEAD_MAX_PCT:.0f}%)")
        else:
            lines.append(label)
    return lines, fails


def _qos_check(old: dict, new: dict):
    """QoS-enforcement gates over the NEW side's ``qos_overload``
    evidence (each run carries its own unloaded baseline); the old
    side's presence only decides gate-vs-skip, matching the insights
    pattern. Returns (report lines, failure strings)."""
    lines, fails = [], []
    for name, cfg in (new.get("configs") or {}).items():
        q = cfg.get("qos") if isinstance(cfg, dict) else None
        if not isinstance(q, dict) or \
                not isinstance(q.get("protected_over_unloaded"),
                               (int, float)):
            continue
        ratio = float(q["protected_over_unloaded"])
        label = (f"  configs.{name:33s} interactive p99 "
                 f"{q.get('interactive_p99_protected_ms')} ms under "
                 f"flood vs {q.get('interactive_p99_unloaded_ms')} ms "
                 f"unloaded ({ratio:.2f}x)")
        ocfg = (old.get("configs") or {}).get(name)
        oq = ocfg.get("qos") if isinstance(ocfg, dict) else None
        if not isinstance(oq, dict):
            lines.append(label + "  SKIPPED gate (first landing — no "
                                 "qos pair in old)")
            continue
        lines.append(label)
        if ratio > QOS_PROTECTED_P99_RATIO_MAX:
            fails.append(f"configs.{name} (interactive p99 {ratio:.2f}x "
                         f"the unloaded baseline under flood — past the "
                         f"{QOS_PROTECTED_P99_RATIO_MAX:.0f}x "
                         f"protection gate)")
        if not q.get("shed_engaged"):
            fails.append(f"configs.{name} (load shedding never engaged "
                         f"during the overload window per the "
                         f"flight-recorder journal)")
        if not q.get("shed_cleared"):
            fails.append(f"configs.{name} (load shedding engaged but "
                         f"never cleared after the flood — hysteresis "
                         f"stuck)")
        if q.get("steady_compiles"):
            fails.append(f"configs.{name} (steady_compiles="
                         f"{q['steady_compiles']} — a priority class "
                         f"leaked into a jit shape key)")
    return lines, fails


def _tier_check(new: dict):
    """Intra-file gates on the NEW side's ``tiered_capacity`` evidence
    (judged against the run's own device-resident baseline, so they
    apply even on the first round with no old side)."""
    out = []
    for name, cfg in (new.get("configs") or {}).items():
        if not isinstance(cfg, dict) or "hot_p99_ratio" not in cfg:
            continue
        ratio = cfg.get("hot_p99_ratio")
        hot, dev = cfg.get("hot_p99_ms", 0), cfg.get("device_p99_ms", 0)
        if isinstance(ratio, (int, float)) and \
                ratio > HOT_P99_RATIO_MAX and \
                float(hot) - float(dev) > HOT_P99_FLOOR_MS:
            out.append(f"configs.{name}: hot-set p99 {hot} ms is "
                       f"{ratio}x the device-resident baseline "
                       f"({dev} ms) — past the {HOT_P99_RATIO_MAX}x "
                       f"acceptance gate")
        if cfg.get("steady_state_rebuilds"):
            out.append(f"configs.{name}: steady_state_rebuilds="
                       f"{cfg['steady_state_rebuilds']} — tier "
                       f"promotions re-packed planes instead of riding "
                       f"the handoff-import path")
        if "journal_consistent" in cfg and not cfg["journal_consistent"]:
            out.append(f"configs.{name}: tier transitions are NOT "
                       f"reconstructable from the flight-recorder "
                       f"journal (journal_consistent=false)")
    return out


def _journal_check(new: dict):
    """Intra-file gates on the NEW side's flight-recorder evidence.

    - A chaos run's ``chaos_journal`` config must show the kill was
      reconstructable from the journal: failover waves + the promotion
      + the warm handoff present, the watchdog capture fired inside the
      failure window, and the red state cleared after.
    - A steady-state run carrying ``watchdog_steady_captures`` must
      show ZERO automatic captures (the false-positive invariant: a
      healthy bench never trips the SLO watchdog).
    Returns failure strings."""
    out = []
    for name, cfg in (new.get("configs") or {}).items():
        if not isinstance(cfg, dict) or "capture_in_window" not in cfg:
            continue
        if not cfg.get("capture_in_window"):
            out.append(f"configs.{name}: watchdog capture did not fire "
                       f"inside the failure window")
        if not cfg.get("watchdog_cleared"):
            out.append(f"configs.{name}: watchdog red state never "
                       f"cleared after the heal")
        for field in ("failover_wave_events", "shard_failover_events",
                      "handoff_manifest_events", "handoff_done_events"):
            if not cfg.get(field):
                out.append(f"configs.{name}: {field}=0 — the kill is "
                           f"not reconstructable from the journal")
    steady = new.get("watchdog_steady_captures")
    if isinstance(steady, (int, float)) and steady > 0:
        out.append(f"watchdog_steady_captures={int(steady)} — the SLO "
                   f"watchdog fired on a steady-state run (false-"
                   f"positive invariant broke)")
    return out


def diff(old: dict, new: dict, threshold: float,
         p99_threshold: float = P99_RISE_MAX):
    """Returns (report lines, regression names)."""
    lines = []
    regressions = []
    om, nm = _metrics(old), _metrics(new)
    for name in sorted(set(om) | set(nm)):
        o, n = om.get(name), nm.get(name)
        if o is None:
            # one-sided config: note it, never gate (a new config is
            # not a regression; the NEXT diff will pair it)
            lines.append(f"  {name:40s} SKIPPED (only in new)"
                         + (f"  {n['value']} {n.get('unit', '')}"
                            if _is_throughput(n) else ""))
            continue
        if n is None:
            lines.append(f"  {name:40s} SKIPPED (only in old)")
            continue
        # chaos configs: the zero-failure invariant + time-to-warm
        # growth gate run for ANY config carrying the fields (these
        # entries are not throughput-shaped, so they are checked before
        # the throughput filter below)
        if isinstance(n, dict) and n.get("failures_after_settle"):
            lines.append(f"  {name:40s} {n['failures_after_settle']} "
                         f"FAILED SEARCHES AFTER FAILOVER SETTLED")
            regressions.append(
                f"{name} ({n['failures_after_settle']} failed searches "
                f"after settle — the zero-failure invariant broke)")
        ow = (o or {}).get("time_to_warm_s") if isinstance(o, dict) \
            else None
        nw = (n or {}).get("time_to_warm_s") if isinstance(n, dict) \
            else None
        if isinstance(ow, (int, float)) and isinstance(nw, (int, float)):
            ln = f"  {name:40s} time_to_warm {ow:.3f} -> {nw:.3f} s"
            if nw > max(TIME_TO_WARM_FLOOR_S,
                        ow * (1 + TIME_TO_WARM_GROW_MAX)):
                ln += "  << TIME-TO-WARM REGRESSION"
                regressions.append(
                    f"{name} (time_to_warm_s {ow:.3f} -> {nw:.3f})")
            lines.append(ln)
        # tier promotion-count drift (tiered_capacity): the seeded Zipf
        # mix makes transition counts near-deterministic, so a jump is
        # the anti-thrash policy degrading into churn
        op_ = o.get("promotions") if isinstance(o, dict) else None
        np_ = n.get("promotions") if isinstance(n, dict) else None
        if isinstance(op_, (int, float)) and \
                isinstance(np_, (int, float)) and \
                isinstance((o or {}).get("hot_p99_ratio"),
                           (int, float)):
            ln = f"  {name:40s} promotions {int(op_)} -> {int(np_)}"
            if np_ > op_ * PROMOTION_DRIFT_FACTOR + PROMOTION_DRIFT_SLACK:
                ln += "  << PROMOTION-CHURN REGRESSION"
                regressions.append(
                    f"{name} (promotions {int(op_)} -> {int(np_)} — "
                    f"tier churn)")
            lines.append(ln)
        # roofline-efficiency gate: per-kernel mean model-vs-achieved
        # efficiency embedded by bench.py's per-config audit delta
        # (checked before the throughput filter so an error-shaped new
        # side still reports its paired efficiency lines)
        oe = o.get("efficiency") if isinstance(o, dict) else None
        ne = n.get("efficiency") if isinstance(n, dict) else None
        if isinstance(oe, dict) and isinstance(ne, dict):
            for kern in sorted(set(oe) | set(ne)):
                ok_, nk_ = oe.get(kern), ne.get(kern)
                if not isinstance(ok_, dict) or \
                        not isinstance(nk_, dict):
                    lines.append(f"  {name:40s} efficiency[{kern}] "
                                 f"SKIPPED (one-sided)")
                    continue
                ov_, nv_ = ok_.get("mean_pct"), nk_.get("mean_pct")
                if not isinstance(ov_, (int, float)) or \
                        not isinstance(nv_, (int, float)) or ov_ <= 0:
                    continue
                if min(int(ok_.get("n", 0)),
                       int(nk_.get("n", 0))) < EFF_MIN_DISPATCHES:
                    continue
                drop = (float(ov_) - float(nv_)) / float(ov_)
                eflag = ""
                if drop > EFF_DROP_MAX:
                    eflag = "  << EFFICIENCY REGRESSION"
                    regressions.append(
                        f"{name} (efficiency[{kern}] {ov_:.2f} -> "
                        f"{nv_:.2f} %, {-drop:+.1%})")
                lines.append(
                    f"  {name:40s} efficiency[{kern}] {ov_:.2f} -> "
                    f"{nv_:.2f} %  {-drop:+7.1%}{eflag}")
        if not _is_throughput(o):
            continue                     # nothing numeric to compare
        if not _is_throughput(n):
            lines.append(f"  {name:40s} {o['value']:>10.1f} -> ERROR "
                         f"({str(n.get('error', 'no value'))[:60]})")
            regressions.append(f"{name} (errored)")
            continue
        ov, nv = float(o["value"]), float(n["value"])
        delta = (nv - ov) / ov if ov else 0.0
        flag = ""
        if delta < -threshold:
            flag = "  << REGRESSION"
            regressions.append(f"{name} ({delta:+.1%})")
        rec = ""
        orec, nrec = o.get("recall_at_k"), n.get("recall_at_k")
        if isinstance(orec, (int, float)) and \
                isinstance(nrec, (int, float)):
            rec = f"  recall {orec:.4f} -> {nrec:.4f}"
            if float(orec) - float(nrec) > RECALL_DROP_MAX:
                flag = "  << RECALL REGRESSION"
                regressions.append(
                    f"{name} (recall_at_k {orec:.4f} -> {nrec:.4f})")
        p99 = ""
        if isinstance(o.get("p99_ms"), (int, float)) and \
                isinstance(n.get("p99_ms"), (int, float)):
            p99 = f"  p99 {o['p99_ms']:.1f} -> {n['p99_ms']:.1f} ms"
            # p99-latency gate: only configs that opted in on BOTH
            # sides (p99_gate: true) — a throughput-only config's p99
            # is too noisy to gate on
            if o.get("p99_gate") and n.get("p99_gate") and \
                    float(o["p99_ms"]) > 0:
                rise = (float(n["p99_ms"]) - float(o["p99_ms"])) \
                    / float(o["p99_ms"])
                if rise > p99_threshold:
                    flag = "  << P99 REGRESSION"
                    regressions.append(
                        f"{name} (p99 {o['p99_ms']:.1f} -> "
                        f"{n['p99_ms']:.1f} ms, {rise:+.0%})")
        dbytes = ""
        for bk in ("text_device_bytes", "knn_device_bytes"):
            ob, nb = o.get(bk), n.get(bk)
            if isinstance(ob, (int, float)) and ob > 0 and \
                    isinstance(nb, (int, float)):
                grow = (float(nb) - float(ob)) / float(ob)
                dbytes += f"  {bk.split('_')[0]} B/dev {int(ob)} -> " \
                          f"{int(nb)}"
                # per-device HBM footprint is the multichip capacity
                # budget — growing it at the same device count is a
                # regression even if qps held (fixed gate: bytes are
                # deterministic, unlike virtual-device qps)
                if grow > DEVICE_BYTES_GROW_MAX:
                    flag = "  << DEVICE-BYTES REGRESSION"
                    regressions.append(
                        f"{name} ({bk} {int(ob)} -> {int(nb)}, "
                        f"{grow:+.0%})")
        lines.append(f"  {name:40s} {ov:>10.1f} -> {nv:>10.1f} "
                     f"{n.get('unit', ''):12s} {delta:+7.1%}{rec}{p99}"
                     f"{dbytes}{flag}")
    return lines, regressions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Diff two BENCH_*.json files; exit 1 on a >threshold "
                    "throughput regression.")
    ap.add_argument("old", help="baseline BENCH json")
    ap.add_argument("new", help="candidate BENCH json")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="relative throughput drop that fails the diff "
                         "(default 0.10 = 10%%)")
    ap.add_argument("--p99-threshold", type=float, default=P99_RISE_MAX,
                    help="relative p99 rise that fails p99-gated configs "
                         "(default 0.25 = 25%%)")
    ap.add_argument("--multichip-threshold", type=float, default=0.35,
                    help="throughput threshold used instead when BOTH "
                         "sides are MULTICHIP sweeps (default 0.35: "
                         "virtual-device CPU qps carries ~30%% "
                         "run-to-run scheduler noise — the bytes and "
                         "scaling gates stay exact)")
    args = ap.parse_args(argv)
    with open(args.old) as f:
        old = _unwrap(json.load(f))
    with open(args.new) as f:
        new = _unwrap(json.load(f))
    if old.get("multichip") and new.get("multichip"):
        args.threshold = max(args.threshold, args.multichip_threshold)
    if old.get("chaos") and new.get("chaos"):
        # recovery-window p99 over a fault-injected window is several-x
        # noisy run to run; the widened gate still catches failover
        # stalls (p99 jumping to seconds) — and the window's qps gets
        # the same treatment (see CHAOS_QPS_DROP_MAX)
        args.p99_threshold = max(args.p99_threshold, CHAOS_P99_RISE_MAX)
        args.threshold = max(args.threshold, CHAOS_QPS_DROP_MAX)
    print(f"bench diff: {args.old} -> {args.new} "
          f"(threshold {args.threshold:.0%}, p99 "
          f"{args.p99_threshold:.0%})")
    if old.get("backend") != new.get("backend"):
        print(f"  NOTE: backends differ ({old.get('backend')} -> "
              f"{new.get('backend')}) — deltas are not apples-to-apples")
    lines, regressions = diff(old, new, args.threshold,
                              args.p99_threshold)
    for ln in lines:
        print(ln)
    if new.get("multichip"):
        # the new sweep must hold its own ~1/n_shards bytes scaling
        # regardless of what the old side measured
        fails = _multichip_scaling_check(new)
        if not (new.get("configs") or {}) and (old.get("configs") or {}):
            fails.append("multichip sweep is empty (no per-device "
                         "configs in tail) but the old side had one — "
                         "the harness regressed to the empty shell")
        for fail in fails:
            print(f"  {fail}")
            regressions.append(fail)
    # flight-recorder evidence gates (chaos journal reconstruction +
    # steady-state zero-capture invariant) judge the NEW side's own
    # record regardless of what the old side measured
    for fail in _journal_check(new):
        print(f"  {fail}")
        regressions.append(fail)
    # tiered-capacity gates judge the NEW run against its own embedded
    # device-resident baseline (hot-set p99 ratio, zero steady-state
    # re-packs, journal reconstructability)
    for fail in _tier_check(new):
        print(f"  {fail}")
        regressions.append(fail)
    # insights-overhead gate: the serving bench's paired on/off windows
    # (skip with a note on the first landing — old side has no pair)
    ins_lines, ins_fails = _insights_check(old, new)
    for ln in ins_lines:
        print(ln)
    regressions.extend(ins_fails)
    # continuous-profiler overhead gate: the serving bench's paired
    # sampler-on/off windows (same first-landing SKIP discipline)
    cp_lines, cp_fails = _contprof_check(old, new)
    for ln in cp_lines:
        print(ln)
    regressions.extend(cp_fails)
    # multi-tenant QoS gates: the overload bench's own three windows
    # (protection ratio, shed engage/clear, zero class-shape compiles) —
    # skip with a note on the first landing, like the insights pair
    qos_lines, qos_fails = _qos_check(old, new)
    for ln in qos_lines:
        print(ln)
    regressions.extend(qos_fails)
    if regressions:
        print(f"FAIL: {len(regressions)} regression(s) (throughput past "
              f"{args.threshold:.0%}, recall_at_k past "
              f"{RECALL_DROP_MAX}, or gated p99 past "
              f"{args.p99_threshold:.0%}):")
        for r in regressions:
            print(f"  - {r}")
        return 1
    print("OK: no throughput, recall, or gated-p99 regression past the "
          "thresholds")
    return 0


if __name__ == "__main__":
    sys.exit(main())
