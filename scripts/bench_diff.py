#!/usr/bin/env python3
"""Diff two ``BENCH_*.json`` result files per config and gate on
throughput regressions.

Usage::

    python scripts/bench_diff.py BENCH_r05.json BENCH_r06.json
    python scripts/bench_diff.py old.json new.json --threshold 0.10

Prints one line per comparable metric — the headline plus every entry in
``configs`` that carries a throughput ``value`` (unit ``*/s``) — with the
old/new numbers, the relative delta, and ``p99_ms`` movement where both
sides report it. Exits **1** when any throughput metric regressed by
more than ``--threshold`` (default 10%), OR when a config's
``recall_at_k`` dropped by more than 0.01 absolute (recall is a
correctness budget, not a throughput — it gets its own, tighter gate),
so CI can ratchet on bench trajectories instead of eyeballing the
``BENCH_r*`` files.

Configs present in only one of the two files are SKIPPED with a note
(added / removed), never gated — BENCH files span rounds where configs
appear and (on backend fallbacks) drop out; a pairwise diff can only
judge what both sides measured. Error-shaped configs (``{"error":
...}``) still gate when the other side had a real number — a config
that stopped producing results IS a regression.
"""

from __future__ import annotations

import argparse
import json
import sys


def _is_throughput(doc) -> bool:
    return (isinstance(doc, dict)
            and isinstance(doc.get("value"), (int, float))
            and str(doc.get("unit", "")).endswith("/s"))


def _unwrap(doc: dict) -> dict:
    """Accept both the raw bench line and the driver's wrapper (the
    ``BENCH_r*.json`` files nest the bench JSON under ``parsed``)."""
    if isinstance(doc.get("parsed"), dict) and (
            "value" in doc["parsed"] or "configs" in doc["parsed"]):
        return doc["parsed"]
    return doc


def _metrics(doc: dict):
    """Flatten one bench JSON into {name: config-doc} — the headline
    (top-level value/unit) under ``<metric>``, then every config."""
    out = {}
    if _is_throughput(doc):
        # stable key: the metric string embeds n_docs, which differs
        # across backends/scales and would break the pairing
        out["headline"] = doc
    for name, cfg in (doc.get("configs") or {}).items():
        out[f"configs.{name}"] = cfg if isinstance(cfg, dict) else {}
    return out


#: absolute recall_at_k drop that fails the diff (recall is a
#: correctness budget — 1% absolute is already a visible quality change)
RECALL_DROP_MAX = 0.01

#: relative p99 increase that fails the diff for p99-gated configs
#: (configs carrying ``p99_gate: true`` — lexical_10m_prune opts in:
#: its whole point is a latency profile, so throughput alone can't
#: certify it)
P99_RISE_MAX = 0.25


def diff(old: dict, new: dict, threshold: float,
         p99_threshold: float = P99_RISE_MAX):
    """Returns (report lines, regression names)."""
    lines = []
    regressions = []
    om, nm = _metrics(old), _metrics(new)
    for name in sorted(set(om) | set(nm)):
        o, n = om.get(name), nm.get(name)
        if o is None:
            # one-sided config: note it, never gate (a new config is
            # not a regression; the NEXT diff will pair it)
            lines.append(f"  {name:40s} SKIPPED (only in new)"
                         + (f"  {n['value']} {n.get('unit', '')}"
                            if _is_throughput(n) else ""))
            continue
        if n is None:
            lines.append(f"  {name:40s} SKIPPED (only in old)")
            continue
        if not _is_throughput(o):
            continue                     # nothing numeric to compare
        if not _is_throughput(n):
            lines.append(f"  {name:40s} {o['value']:>10.1f} -> ERROR "
                         f"({str(n.get('error', 'no value'))[:60]})")
            regressions.append(f"{name} (errored)")
            continue
        ov, nv = float(o["value"]), float(n["value"])
        delta = (nv - ov) / ov if ov else 0.0
        flag = ""
        if delta < -threshold:
            flag = "  << REGRESSION"
            regressions.append(f"{name} ({delta:+.1%})")
        rec = ""
        orec, nrec = o.get("recall_at_k"), n.get("recall_at_k")
        if isinstance(orec, (int, float)) and \
                isinstance(nrec, (int, float)):
            rec = f"  recall {orec:.4f} -> {nrec:.4f}"
            if float(orec) - float(nrec) > RECALL_DROP_MAX:
                flag = "  << RECALL REGRESSION"
                regressions.append(
                    f"{name} (recall_at_k {orec:.4f} -> {nrec:.4f})")
        p99 = ""
        if isinstance(o.get("p99_ms"), (int, float)) and \
                isinstance(n.get("p99_ms"), (int, float)):
            p99 = f"  p99 {o['p99_ms']:.1f} -> {n['p99_ms']:.1f} ms"
            # p99-latency gate: only configs that opted in on BOTH
            # sides (p99_gate: true) — a throughput-only config's p99
            # is too noisy to gate on
            if o.get("p99_gate") and n.get("p99_gate") and \
                    float(o["p99_ms"]) > 0:
                rise = (float(n["p99_ms"]) - float(o["p99_ms"])) \
                    / float(o["p99_ms"])
                if rise > p99_threshold:
                    flag = "  << P99 REGRESSION"
                    regressions.append(
                        f"{name} (p99 {o['p99_ms']:.1f} -> "
                        f"{n['p99_ms']:.1f} ms, {rise:+.0%})")
        lines.append(f"  {name:40s} {ov:>10.1f} -> {nv:>10.1f} "
                     f"{n.get('unit', ''):12s} {delta:+7.1%}{rec}{p99}"
                     f"{flag}")
    return lines, regressions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Diff two BENCH_*.json files; exit 1 on a >threshold "
                    "throughput regression.")
    ap.add_argument("old", help="baseline BENCH json")
    ap.add_argument("new", help="candidate BENCH json")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="relative throughput drop that fails the diff "
                         "(default 0.10 = 10%%)")
    ap.add_argument("--p99-threshold", type=float, default=P99_RISE_MAX,
                    help="relative p99 rise that fails p99-gated configs "
                         "(default 0.25 = 25%%)")
    args = ap.parse_args(argv)
    with open(args.old) as f:
        old = _unwrap(json.load(f))
    with open(args.new) as f:
        new = _unwrap(json.load(f))
    print(f"bench diff: {args.old} -> {args.new} "
          f"(threshold {args.threshold:.0%}, p99 "
          f"{args.p99_threshold:.0%})")
    if old.get("backend") != new.get("backend"):
        print(f"  NOTE: backends differ ({old.get('backend')} -> "
              f"{new.get('backend')}) — deltas are not apples-to-apples")
    lines, regressions = diff(old, new, args.threshold,
                              args.p99_threshold)
    for ln in lines:
        print(ln)
    if regressions:
        print(f"FAIL: {len(regressions)} regression(s) (throughput past "
              f"{args.threshold:.0%}, recall_at_k past "
              f"{RECALL_DROP_MAX}, or gated p99 past "
              f"{args.p99_threshold:.0%}):")
        for r in regressions:
            print(f"  - {r}")
        return 1
    print("OK: no throughput, recall, or gated-p99 regression past the "
          "thresholds")
    return 0


if __name__ == "__main__":
    sys.exit(main())
