"""Full-corpus YAML conformance sweep against a 3-node TCP cluster.

Runs every rest-api-spec suite through a non-master node's cluster REST
front and prints a per-directory score plus the corpus total, for
comparison with the single-node sweep (tests/test_yaml_conformance.py).

Usage:  python scripts/cluster_conformance_sweep.py [suite-prefix ...]
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from elasticsearch_tpu.node.cluster_node import ClusterNode  # noqa: E402
from elasticsearch_tpu.testkit.yaml_runner import (  # noqa: E402
    REFERENCE_SPEC_ROOT, YamlTestRunner)

BASE_PORT = 29700


def main():
    prefixes = sys.argv[1:]
    d = tempfile.mkdtemp(prefix="cluster_sweep_")
    peers = {f"n{i}": ("127.0.0.1", BASE_PORT + i) for i in range(3)}
    nodes = [ClusterNode(f"n{i}", "127.0.0.1", BASE_PORT + i, peers,
                         os.path.join(d, f"n{i}"), seed=i)
             for i in range(3)]
    leader = None
    deadline = time.monotonic() + 20.0
    while leader is None and time.monotonic() < deadline:
        ls = [n for n in nodes if n.coordinator.mode == "LEADER"]
        if len(ls) == 1:
            leader = ls[0]
        time.sleep(0.05)
    assert leader is not None
    client = nodes[(nodes.index(leader) + 1) % 3]
    print(f"# 3-node cluster up; REST front: {client.node_id} "
          f"(master: {leader.node_id})", file=sys.stderr)

    class Target:
        def handle(self, m, p, q, b):
            return client.rest.handle(m, p, q or "", b or b"")

    def factory():
        import shutil
        rest = client.rest
        rest.handle("DELETE", "/*", "expand_wildcards=all", b"")
        # wipe snapshot repositories (the reference test framework's
        # wipeRepositories between suites): registration is replicated,
        # the blob dirs are shared — clear both on every node
        for n in nodes:
            snaps = n.rest.api.snapshots
            for name, repo in list(snaps.repositories.items()):
                shutil.rmtree(repo.location, ignore_errors=True)
                snaps.repositories.pop(name, None)
        with rest.lock:
            templates = list(rest.api.templates)
            comps = list(rest.api.component_templates)
            idx_t = list(getattr(rest.api, "index_templates", {}) or {})
        for t in templates:
            rest.handle("DELETE", f"/_template/{t}", "", b"")
        for t in idx_t:
            rest.handle("DELETE", f"/_index_template/{t}", "", b"")
        for t in comps:
            rest.handle("DELETE", f"/_component_template/{t}", "", b"")
        return Target()

    runner = YamlTestRunner(factory)
    files = runner.discover()
    if prefixes:
        root = os.path.join(REFERENCE_SPEC_ROOT, "test")
        files = [f for f in files
                 if any(os.path.relpath(f, root).startswith(p)
                        for p in prefixes)]
    by_dir = {}
    total = passed = 0
    t0 = time.time()
    try:
        for i, f in enumerate(files):
            try:
                results = runner.run_file(f)
            except Exception as e:   # noqa: BLE001 — suite-level crash
                results = []
                print(f"# suite crash {f}: {e}", file=sys.stderr)
            for r in results:
                total += 1
                top = r.suite.split("/")[0]
                cur = by_dir.setdefault(top, [0, 0])
                cur[1] += 1
                if r.ok:
                    passed += 1
                    cur[0] += 1
                elif os.environ.get("SWEEP_VERBOSE"):
                    print(f"FAIL {r.suite} :: {r.name} :: "
                          f"{r.reason[:300]}", file=sys.stderr)
            if (i + 1) % 25 == 0:
                print(f"# {i + 1}/{len(files)} files, {passed}/{total} "
                      f"({time.time() - t0:.0f}s)", file=sys.stderr)
    finally:
        for n in nodes:
            try:
                n.stop()
            except Exception:   # noqa: BLE001
                pass
    for name in sorted(by_dir):
        p, t = by_dir[name]
        flag = "" if p == t else f"   <-- {t - p} failing"
        print(f"{name:45s} {p:4d}/{t:<4d}{flag}")
    print(json.dumps({"cluster_conformance_pass": passed,
                      "total": total,
                      "pct": round(100.0 * passed / max(total, 1), 1)}))


if __name__ == "__main__":
    main()
