#!/usr/bin/env python
"""Pretty-print a span tree from a running node's trace store.

Usage:
    python scripts/trace_dump.py TRACE_ID [--host http://127.0.0.1:9200]
    python scripts/trace_dump.py --last [--host ...]   # newest trace
    python scripts/trace_dump.py --list [--host ...]   # recent trace ids

``--last`` reads the node's ``GET /_trace`` listing (newest-first trace
index with root action + duration) and dumps the newest trace — no more
probe-request guessing; if the store is empty it issues one probe
request to mint a trace. ``--list`` prints the listing itself.

Output, one line per span, indented by tree depth:

    rest[indices:data/read/search]              12.41ms  node=n0
      coordinator[search]                       11.80ms  indices=logs
        shards[logs]                            11.02ms
          plane_dispatch                         9.13ms  compile_cache=hit
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.request


def _get(host: str, path: str, headers=None):
    req = urllib.request.Request(host.rstrip("/") + path,
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, dict(r.headers), r.read()


def _fmt_attrs(span: dict) -> str:
    parts = []
    if span.get("node"):
        parts.append(f"node={span['node']}")
    for k, v in (span.get("attrs") or {}).items():
        if isinstance(v, float):
            v = round(v, 2)
        parts.append(f"{k}={v}")
    return "  ".join(parts)


def print_tree(spans: list, depth: int = 0) -> None:
    for span in spans:
        name = "  " * depth + span.get("name", "?")
        took = f"{span.get('took_ms', 0):9.2f}ms"
        print(f"{name:<48}{took}  {_fmt_attrs(span)}".rstrip())
        print_tree(span.get("children") or [], depth + 1)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace_id", nargs="?", help="trace id to dump")
    ap.add_argument("--host", default="http://127.0.0.1:9200")
    ap.add_argument("--last", action="store_true",
                    help="dump the newest trace from the GET /_trace "
                         "listing")
    ap.add_argument("--list", action="store_true", dest="list_traces",
                    help="print the recent-trace listing and exit")
    ap.add_argument("--json", action="store_true",
                    help="raw JSON instead of the tree rendering")
    args = ap.parse_args()
    tid = args.trace_id

    def _listing():
        status, _h, body = _get(args.host, "/_trace")
        if status != 200:
            print(f"GET /_trace -> {status}: {body[:300]!r}",
                  file=sys.stderr)
            return None
        return json.loads(body).get("traces") or []

    if args.list_traces:
        rows = _listing()
        if rows is None:
            return 1
        for row in rows:
            print(f"{row['trace_id']}  "
                  f"{row.get('took_ms', 0):9.2f}ms  "
                  f"{row.get('root', '?')}  "
                  f"spans={row.get('span_count', 0)}")
        return 0
    if args.last:
        rows = _listing()
        if rows is None:
            return 1
        if not rows:
            # empty store: one probe request mints a trace
            _get(args.host, "/")
            rows = _listing() or []
        if not rows:
            print("trace store is empty", file=sys.stderr)
            return 2
        tid = rows[0]["trace_id"]
    if not tid:
        ap.error("pass TRACE_ID, --last or --list")
    status, _headers, body = _get(args.host, f"/_trace/{tid}")
    if status != 200:
        print(f"GET /_trace/{tid} -> {status}: {body[:300]!r}",
              file=sys.stderr)
        return 1
    doc = json.loads(body)
    if args.json:
        json.dump(doc, sys.stdout, indent=2)
        print()
        return 0
    print(f"trace {doc['trace_id']} — {doc['span_count']} span(s)"
          + (f", {doc['dropped_spans']} dropped"
             if doc.get("dropped_spans") else ""))
    print_tree(doc["tree"])
    return 0


if __name__ == "__main__":
    sys.exit(main())
