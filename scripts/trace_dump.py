#!/usr/bin/env python
"""Pretty-print a span tree from a running node's trace store.

Usage:
    python scripts/trace_dump.py TRACE_ID [--host http://127.0.0.1:9200]
    python scripts/trace_dump.py --last [--host ...]   # newest trace
    python scripts/trace_dump.py --list [--min-ms 100] [--tenant T]
    python scripts/trace_dump.py TRACE_ID --events     # + journal events

``--last`` reads the node's ``GET /_trace`` listing (newest-first trace
index with root action + duration) and dumps the newest trace — no more
probe-request guessing; if the store is empty it issues one probe
request to mint a trace. ``--list`` prints the listing itself;
``--min-ms`` and ``--tenant`` pass through to the server-side
``GET /_trace?min_ms=&tenant=`` filters (applied BEFORE the listing
cap, so they surface the newest matching traces).

``--events`` additionally fetches the flight-recorder journal
(``GET /_flight_recorder?trace_id=...``) and interleaves each event into
the span tree at the deepest span whose window contains the event's
timestamp — a failover wave or breaker trip renders INSIDE the request
that felt it.

``--chrome PATH`` writes the span tree (plus the ``--events`` journal
interleave when requested) as Chrome trace-event JSON — the SAME format
``GET /_profiler/timeline`` serves for dispatch timelines — so a
request's span tree and the dispatch timeline that served it load
side-by-side in perfetto/chrome://tracing: spans render as complete
``X`` events (one process per node, nested by time containment),
journal events as instant ``i`` marks.

Output, one line per span, indented by tree depth:

    rest[indices:data/read/search]              12.41ms  node=n0
      coordinator[search]                       11.80ms  indices=logs
        shards[logs]                            11.02ms
          plane_dispatch                         9.13ms  compile_cache=hit
          * failover_wave                        @+3.20ms  failed=n2
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.parse
import urllib.request
import zlib


def _get(host: str, path: str, headers=None):
    req = urllib.request.Request(host.rstrip("/") + path,
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=10) as r:
        return r.status, dict(r.headers), r.read()


def _fmt_attrs(span: dict) -> str:
    parts = []
    if span.get("node"):
        parts.append(f"node={span['node']}")
    for k, v in (span.get("attrs") or {}).items():
        if isinstance(v, float):
            v = round(v, 2)
        parts.append(f"{k}={v}")
    return "  ".join(parts)


def attach_events(tree: list, events: list) -> list:
    """Hang each journal event off the DEEPEST span whose
    [start, start+took] window contains the event's wall timestamp;
    events outside every span surface at the root. Returns the events
    that attached nowhere."""
    def best_span(spans, ts):
        for span in spans:
            s0 = span.get("start_ms")
            if s0 is None:
                continue
            if s0 <= ts <= s0 + max(span.get("took_ms", 0), 0):
                deeper = best_span(span.get("children") or [], ts)
                return deeper if deeper is not None else span
        return None

    orphans = []
    for ev in events:
        host = best_span(tree, ev.get("ts_ms", 0))
        if host is None:
            orphans.append(ev)
        else:
            host.setdefault("_events", []).append(ev)
    return orphans


def _print_event(ev: dict, depth: int, base_ms=None) -> None:
    name = "  " * depth + "* " + ev.get("type", "?")
    when = f"@{ev.get('ts_ms', 0):.0f}" if base_ms is None else \
        f"@+{ev.get('ts_ms', 0) - base_ms:.2f}ms"
    parts = [when]
    if ev.get("node"):
        parts.append(f"node={ev['node']}")
    for k, v in (ev.get("attrs") or {}).items():
        if isinstance(v, float):
            v = round(v, 2)
        parts.append(f"{k}={v}")
    print(f"{name:<48}{'':>9}  {'  '.join(parts)}".rstrip())


def print_tree(spans: list, depth: int = 0) -> None:
    for span in spans:
        name = "  " * depth + span.get("name", "?")
        took = f"{span.get('took_ms', 0):9.2f}ms"
        print(f"{name:<48}{took}  {_fmt_attrs(span)}".rstrip())
        base = span.get("start_ms")
        # interleave children spans + attached events by start time
        kids = [("span", c) for c in span.get("children") or []]
        kids += [("event", e) for e in span.get("_events") or []]
        kids.sort(key=lambda kv: kv[1].get("start_ms", kv[1].get(
            "ts_ms", 0)))
        for kind, item in kids:
            if kind == "span":
                print_tree([item], depth + 1)
            else:
                _print_event(item, depth + 1, base_ms=base)


def chrome_from_spans(doc: dict, events=None) -> dict:
    """Span tree + journal events -> Chrome trace-event JSON.

    One *process* per emitting node (pid derived from the node name the
    same way ``search/dispatch_profile.chrome_trace`` derives batcher
    pids, so a merged load never conflates nodes); spans become
    complete ``X`` events that nest by time containment on one track,
    journal events become instant ``i`` marks at their wall
    timestamp."""
    out = []
    named = set()

    def pid_of(node: str) -> int:
        pid = (zlib.crc32(f"trace\x00{node}".encode()) & 0x3FFFFFFF) | 1
        if pid not in named:
            named.add(pid)
            out.append({"ph": "M", "name": "process_name", "pid": pid,
                        "ts": 0, "args": {"name": f"{node} trace"}})
            out.append({"ph": "M", "name": "thread_name", "pid": pid,
                        "tid": 1, "ts": 0, "args": {"name": "spans"}})
        return pid

    def walk(spans):
        for span in spans:
            node = str(span.get("node") or "local")
            args = {k: v for k, v in (span.get("attrs") or {}).items()}
            if span.get("span_id"):
                args["span_id"] = span["span_id"]
            out.append({
                "ph": "X", "name": str(span.get("name", "?")),
                "cat": "span", "pid": pid_of(node), "tid": 1,
                "ts": round(float(span.get("start_ms", 0)) * 1e3, 1),
                "dur": round(max(float(span.get("took_ms", 0)), 0.0)
                             * 1e3, 1),
                "args": args})
            walk(span.get("children") or [])

    walk(doc.get("tree") or [])
    for ev in events or []:
        node = str(ev.get("node") or "local")
        args = dict(ev.get("attrs") or {})
        if ev.get("trace_id"):
            args["trace_id"] = ev["trace_id"]
        out.append({
            "ph": "i", "name": str(ev.get("type", "?")), "cat": "journal",
            "pid": pid_of(node), "tid": 1, "s": "p",
            "ts": round(float(ev.get("ts_ms", 0)) * 1e3, 1),
            "args": args})
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "otherData": {"trace_id": doc.get("trace_id")}}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("trace_id", nargs="?", help="trace id to dump")
    ap.add_argument("--host", default="http://127.0.0.1:9200")
    ap.add_argument("--last", action="store_true",
                    help="dump the newest trace from the GET /_trace "
                         "listing")
    ap.add_argument("--list", action="store_true", dest="list_traces",
                    help="print the recent-trace listing and exit")
    ap.add_argument("--min-ms", type=float, default=None,
                    help="with --list/--last: keep only traces at least "
                         "this slow (server-side GET /_trace?min_ms=)")
    ap.add_argument("--tenant", default=None,
                    help="with --list/--last: keep only one tenant's "
                         "traces (server-side GET /_trace?tenant=, the "
                         "X-Opaque-Id stamped on the root span)")
    ap.add_argument("--json", action="store_true",
                    help="raw JSON instead of the tree rendering")
    ap.add_argument("--events", action="store_true",
                    help="interleave flight-recorder journal events "
                         "(GET /_flight_recorder?trace_id=...) into the "
                         "span tree")
    ap.add_argument("--chrome", metavar="PATH", default=None,
                    help="write the span tree (and --events journal) as "
                         "Chrome trace-event JSON loadable in perfetto "
                         "next to GET /_profiler/timeline output")
    args = ap.parse_args()
    tid = args.trace_id

    def _listing():
        qs = []
        if args.min_ms is not None:
            qs.append(f"min_ms={args.min_ms:g}")
        if args.tenant:
            qs.append("tenant=" + urllib.parse.quote(args.tenant))
        path = "/_trace" + ("?" + "&".join(qs) if qs else "")
        status, _h, body = _get(args.host, path)
        if status != 200:
            print(f"GET {path} -> {status}: {body[:300]!r}",
                  file=sys.stderr)
            return None
        return json.loads(body).get("traces") or []

    if args.list_traces:
        rows = _listing()
        if rows is None:
            return 1
        for row in rows:
            line = (f"{row['trace_id']}  "
                    f"{row.get('took_ms', 0):9.2f}ms  "
                    f"{row.get('root', '?')}  "
                    f"spans={row.get('span_count', 0)}")
            if row.get("tenant"):
                line += f"  tenant={row['tenant']}"
            print(line)
        return 0
    if args.last:
        rows = _listing()
        if rows is None:
            return 1
        if not rows:
            # empty store: one probe request mints a trace
            _get(args.host, "/")
            rows = _listing() or []
        if not rows:
            print("trace store is empty", file=sys.stderr)
            return 2
        tid = rows[0]["trace_id"]
    if not tid:
        ap.error("pass TRACE_ID, --last or --list")
    status, _headers, body = _get(args.host, f"/_trace/{tid}")
    if status != 200:
        print(f"GET /_trace/{tid} -> {status}: {body[:300]!r}",
              file=sys.stderr)
        return 1
    doc = json.loads(body)
    events = []
    if args.events:
        status, _h, ebody = _get(
            args.host, f"/_flight_recorder?trace_id={tid}&limit=512")
        if status == 200:
            events = json.loads(ebody).get("events") or []
        else:
            print(f"GET /_flight_recorder -> {status} (events omitted)",
                  file=sys.stderr)
    if args.chrome:
        with open(args.chrome, "w") as f:
            json.dump(chrome_from_spans(doc, events), f)
        print(f"wrote {args.chrome} (load in ui.perfetto.dev or "
              f"chrome://tracing)")
        return 0
    if args.json:
        if events:
            doc["events"] = events
        json.dump(doc, sys.stdout, indent=2)
        print()
        return 0
    print(f"trace {doc['trace_id']} — {doc['span_count']} span(s)"
          + (f", {doc['dropped_spans']} dropped"
             if doc.get("dropped_spans") else "")
          + (f", {len(events)} journal event(s)" if events else ""))
    orphans = attach_events(doc["tree"], events) if events else []
    print_tree(doc["tree"])
    for ev in orphans:
        _print_event(ev, 0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
