#!/usr/bin/env python
"""Telemetry catalogue lint: runtime registry vs TELEMETRY.md.

The TELEMETRY.md metric catalogue drifted once already (the
``es_plane_swap_ms`` row shipped without its ``kind`` label). This lint
makes drift a CI failure instead of a doc bug:

1. drives a miniature workload through the real stack (RestAPI + index
   + plane search + forced jitted dispatch + repack) so every metric
   family the engine can register at runtime actually registers;
2. snapshots the process registry (``telemetry.DEFAULT.stats_doc()``);
3. parses every backticked ``es_*`` family name out of TELEMETRY.md;
4. fails when a runtime family is undocumented, or a documented family
   can neither be produced by the workload nor explained by the
   CONDITIONAL allowlist below.

Run directly (``python scripts/telemetry_lint.py``) or through the
tier-1 suite (``tests/test_task_resources.py::test_telemetry_lint``).
"""

from __future__ import annotations

import json
import os
import re
import sys
import tempfile

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TELEMETRY_MD = os.path.join(REPO_ROOT, "TELEMETRY.md")

#: documented families the lint workload cannot produce, with the reason
#: they are still correct documentation
CONDITIONAL = {
    # registered only on cluster fronts (ARS EWMAs need peers)
    "es_adaptive_selection_response_seconds":
        "cluster fronts only (adaptive replica selection)",
}

_NAME_RE = re.compile(r"`(es_[a-z0-9_]+)`")


def documented_families(path: str = TELEMETRY_MD) -> set:
    with open(path) as f:
        text = f.read()
    return set(_NAME_RE.findall(text))


def runtime_families() -> set:
    """Register every producible family by exercising the real stack."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if REPO_ROOT not in sys.path:
        sys.path.insert(0, REPO_ROOT)
    from elasticsearch_tpu.common import telemetry
    from elasticsearch_tpu.node.indices_service import IndicesService
    from elasticsearch_tpu.rest.api import RestAPI

    with tempfile.TemporaryDirectory() as d:
        api = RestAPI(IndicesService(d))
        api.handle("PUT", "/lint", "", json.dumps(
            {"mappings": {"properties": {
                "body": {"type": "text"},
                "vec": {"type": "dense_vector", "dims": 4}}}}).encode())
        api.handle("PUT", "/lint/_doc/1", "refresh=true", json.dumps(
            {"body": "quick brown fox", "vec": [1, 0, 0, 0]}).encode())
        # text plane dispatch (+ latency family with exemplar)
        api.handle("POST", "/lint/_search", "", json.dumps(
            {"query": {"match": {"body": "quick"}}}).encode())
        # plane-path request cache hit/miss counters
        api.handle("POST", "/lint/_search", "", json.dumps(
            {"query": {"match": {"body": "quick"}}}).encode())
        # kNN plane dispatch
        api.handle("POST", "/lint/_search", "", json.dumps(
            {"knn": {"field": "vec", "query_vector": [1, 0, 0, 0],
                     "k": 1, "num_candidates": 5}}).encode())
        # delta tier + sync repack path (delta-serve + rebuild families)
        svc = api.indices.get("lint")
        svc.plane_cache.repack_mode = "sync"
        # force the block-max tier onto the repacked generation so the
        # es_lex_* families register: a pruned dispatch (track_total_hits
        # bounded → prune defaults on) and an explicit prune=off (the
        # drift counter the plane_serving health indicator reads)
        svc.plane_cache.lex_prune_min_docs = 1
        api.handle("PUT", "/lint/_doc/2", "refresh=true", json.dumps(
            {"body": "quick red fox"}).encode())
        api.handle("POST", "/lint/_search", "", json.dumps(
            {"query": {"match": {"body": "quick"}}}).encode())
        # second delta doc pushes past REPACK_DELTA_FRACTION: the sync
        # repack folds the delta into a fresh base that now carries the
        # block-max tier (lex_prune_min_docs=1 above)
        api.handle("PUT", "/lint/_doc/3", "refresh=true", json.dumps(
            {"body": "quick blue fox"}).encode())
        api.handle("POST", "/lint/_search", "request_cache=false",
                   json.dumps({"query": {"match": {"body": "quick"}},
                               "track_total_hits": 10}).encode())
        api.handle("POST", "/lint/_search", "request_cache=false",
                   json.dumps({"query": {"match": {"body": "quick"}},
                               "prune": False}).encode())
        # forced jitted dispatch so the XLA compile/transfer families
        # register even on the CPU test backend (host-eager otherwise)
        import numpy as np
        from elasticsearch_tpu.parallel import (DistributedSearchPlane,
                                                make_search_mesh)
        from elasticsearch_tpu.utils.synth import synthetic_csr_corpus_fast
        import jax
        rng = np.random.RandomState(7)
        corpus = synthetic_csr_corpus_fast(rng, 128, 64, 8, zipf_s=1.2)
        corpus["term_ids"] = {f"t{t}": t for t in range(64)}
        mesh = make_search_mesh(n_shards=1, n_replicas=1,
                                devices=jax.devices()[:1])
        plane = DistributedSearchPlane(mesh, [corpus], field="body")
        plane._host_csr = None
        plane.serve([["t1"]], k=4, with_totals=True)
        # IVF (cluster-pruned ANN) dispatch: registers the es_ann_*
        # families (clusters probed / candidates re-ranked / bytes per
        # tier), plus the nprobe-below-default drift counter the
        # plane_serving health indicator reads
        from elasticsearch_tpu.parallel.dist_search import \
            DistributedKnnPlane
        kvecs = rng.randn(256, 8).astype(np.float32)
        kplane = DistributedKnnPlane(
            mesh, [dict(vectors=kvecs)], similarity="cosine",
            ivf=dict(nlist=8, seed=0))
        kplane.serve(np.zeros((2, 8), np.float32), k=3)
        kplane.serve(np.zeros((1, 8), np.float32), k=3, nprobe=1)

        snap = telemetry.DEFAULT.stats_doc()
        return {name for name in snap if name.startswith("es_")}


def main() -> int:
    documented = documented_families()
    runtime = runtime_families()
    rc = 0
    undocumented = sorted(runtime - documented)
    if undocumented:
        rc = 1
        print("UNDOCUMENTED runtime families (add TELEMETRY.md rows):",
              file=sys.stderr)
        for n in undocumented:
            print(f"  {n}", file=sys.stderr)
    stale = sorted(documented - runtime - set(CONDITIONAL))
    if stale:
        rc = 1
        print("STALE documented families (never registered by the lint "
              "workload; remove the row or add a CONDITIONAL entry with "
              "a reason):", file=sys.stderr)
        for n in stale:
            print(f"  {n}", file=sys.stderr)
    phantom = sorted(set(CONDITIONAL) & runtime)
    if phantom:
        # informational only: the process-scoped registry may carry
        # families from OTHER stacks in this process (a cluster test
        # that ran earlier in the same pytest session) — documented +
        # registered is never drift
        print("note: CONDITIONAL families present in this process: "
              + ", ".join(phantom))
    if rc == 0:
        print(f"telemetry lint OK: {len(runtime)} runtime families "
              f"match TELEMETRY.md ({len(CONDITIONAL)} conditional)")
    return rc


if __name__ == "__main__":
    sys.exit(main())
