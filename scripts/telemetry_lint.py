#!/usr/bin/env python
"""Telemetry catalogue lint — thin shim over estpulint rule family 3.

The original standalone lint grew into the analyzer's catalogue rules
(``elasticsearch_tpu/devtools/rules_catalogue.py``, ESTP-C01/C02/C03 —
run them all via ``scripts/estpulint.py``). This entry point survives
for operator muscle memory and for the tier-1 test that invokes it
(``tests/test_task_resources.py::test_telemetry_lint``): same workload,
same output contract, same exit code.
"""

from __future__ import annotations

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from elasticsearch_tpu.devtools.rules_catalogue import (     # noqa: E402
    CONDITIONAL, documented_families, runtime_families)
from elasticsearch_tpu.devtools.rules_catalogue import main as _main  # noqa: E402,E501

TELEMETRY_MD = os.path.join(REPO_ROOT, "TELEMETRY.md")


def main() -> int:
    return _main(REPO_ROOT)


if __name__ == "__main__":
    sys.exit(main())
